"""Host-side bookkeeping for the paged KV pool (vLLM-style paging).

The device arena lives in models/transformer.py (``init_kv_pool`` — a
``[L, NB, bs, H, Dh]`` block array addressed through per-stream block
tables). This module owns everything the device must not: the free
list, per-block refcounts, and the **content-addressed prefix cache**
that lets the shared system prompts dominating real LLM traffic hit
warm KV blocks instead of recomputing prefill.

Addressing is a block-aligned sha256 *chain*::

    h_0 = sha256(tokens[0:bs])
    h_j = sha256(hex(h_{j-1}) || tokens[j*bs:(j+1)*bs])

so a block's digest commits to the entire prefix before it — two
prompts share block ``j`` iff their first ``(j+1)*bs`` tokens are
identical, which is exactly the condition under which their KV rows
match. Divergence is therefore detected at block granularity with no
token-by-token comparison, and a cached chain is only ever adopted as
a consecutive prefix.

Sharing discipline (what makes the in-graph scatter writes safe):

* only FULL prompt blocks are committed, and lookup callers cap
  adoption at ``(plen - 1) // bs`` blocks, so the first block a
  decode step writes (position ``plen``) is always stream-private —
  shared blocks are read-only by construction;
* the cache holds one refcount on each committed block; active
  streams hold one each. Eviction (LRU, leaf-first via per-entry kid
  counters) only touches blocks whose sole reference is the cache's,
  so a block under an active stream can never return to the free
  list;
* :meth:`cow` is the copy-on-write escape hatch for callers that DO
  need to mutate a shared block (e.g. a future partial-block sharing
  scheme): it hands back a private phys id and tells the caller
  whether a device-side ``pool_copy_block`` is required.

Pools register in :data:`POOL_TABLE` (weakly) so obs/metrics.py can
render ``nns_kv_blocks_{free,used,cached}`` and the prefix-cache hit
ratio without holding a pool alive.
"""
from __future__ import annotations

import hashlib
import threading
import weakref
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import flowmarks as flow
from ..utils.atomic import Counters

_POOL_LOCK = threading.Lock()
POOL_TABLE: "weakref.WeakValueDictionary[str, KVBlockPool]" = \
    weakref.WeakValueDictionary()


def chain_hashes(tokens, block_size: int) -> List[str]:
    """Digest chain over the FULL blocks of ``tokens`` (partial tail
    blocks are never hashed — they are never shareable)."""
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32).ravel())
    out: List[str] = []
    prev = b""
    for j in range(arr.size // block_size):
        blk = arr[j * block_size:(j + 1) * block_size]
        h = hashlib.sha256(prev + blk.tobytes()).hexdigest()
        out.append(h)
        prev = h.encode("ascii")
    return out


class _CacheEntry:
    __slots__ = ("phys", "parent", "kids")

    def __init__(self, phys: int, parent: Optional[str]):
        self.phys = phys
        self.parent = parent
        self.kids = 0          # cached children chaining off this block


class KVBlockPool:
    """Free-list allocator + refcounts + LRU prefix cache for one
    device block arena. All methods are thread-safe; ``_lock`` is a
    LEAF lock (no method calls out while holding it)."""

    def __init__(self, n_blocks: int, block_size: int,
                 name: str = "kvpool"):
        if n_blocks < 1 or block_size < 1:
            raise ValueError("n_blocks and block_size must be >= 1")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.name = name
        self._lock = threading.RLock()
        self._free: deque = deque(range(self.n_blocks))
        self._ref = [0] * self.n_blocks
        # insertion order == LRU order; move_to_end on every touch
        self._cache: "OrderedDict[str, _CacheEntry]" = OrderedDict()
        self.stats = Counters(prefix_hits=0, prefix_misses=0,
                              prefix_evictions=0, alloc_failures=0)
        with _POOL_LOCK:
            key, n = name, 1
            while key in POOL_TABLE:
                n += 1
                key = f"{name}-{n}"
            self.name = key
            POOL_TABLE[key] = self

    # -- allocation ----------------------------------------------------

    @flow.acquires("kv-block")
    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` fresh blocks (refcount 1 each), evicting LRU
        cache leaves as needed. None when the pool cannot satisfy the
        request even after eviction — the scheduler's admission
        backpressure signal."""
        if n <= 0:
            return []
        with self._lock:
            while len(self._free) < n and self._evict_one_locked():
                pass
            if len(self._free) < n:
                self.stats.inc("alloc_failures")
                return None
            out = [self._free.popleft() for _ in range(n)]
            for p in out:
                self._ref[p] = 1
            return out

    @flow.acquires("kv-block")
    def retain(self, phys: Sequence[int]) -> None:
        with self._lock:
            for p in phys:
                if self._ref[p] <= 0:
                    raise ValueError(f"retain of free block {p}")
                self._ref[p] += 1

    @flow.settles("kv-block")
    def release(self, phys: Sequence[int]) -> None:
        """Drop one reference per block; blocks whose count reaches
        zero return to the free list (cache-committed blocks keep the
        cache's reference and stay warm)."""
        with self._lock:
            for p in phys:
                if self._ref[p] <= 0:
                    raise ValueError(f"release of free block {p}")
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    self._free.append(p)

    @flow.acquires("kv-block")
    def cow(self, phys: int) -> tuple:
        """Copy-on-write: -> (phys', needs_copy). A sole owner keeps
        its block; a shared block costs one fresh block (the caller
        runs ``pool_copy_block(pool, phys, phys')`` on device and then
        ``release([phys])`` to drop its old reference)."""
        with self._lock:
            if self._ref[phys] <= 0:
                raise ValueError(f"cow of free block {phys}")
            if self._ref[phys] == 1:
                return phys, False
        fresh = self.alloc(1)
        if fresh is None:
            return phys, False          # degraded: caller keeps sharing
        return fresh[0], True

    # -- prefix cache --------------------------------------------------

    @flow.acquires("kv-block")
    def lookup(self, hashes: Sequence[str]) -> List[int]:
        """Adopt the longest cached consecutive prefix of ``hashes``.
        Returned blocks are retained for the caller (release when the
        stream ends) and touched to the LRU hot end. Per-block
        hit/miss counts feed the exported hit ratio."""
        out: List[int] = []
        with self._lock:
            for h in hashes:
                ent = self._cache.get(h)
                if ent is None:
                    break
                self._cache.move_to_end(h)
                self._ref[ent.phys] += 1
                out.append(ent.phys)
            self.stats.add(prefix_hits=len(out),
                           prefix_misses=len(hashes) - len(out))
        return out

    def commit(self, hashes: Sequence[str], phys: Sequence[int]) -> None:
        """Publish a stream's FULL prompt blocks under their chain
        digests. Blocks already cached (under a different stream's
        phys) are left alone; new entries take one cache reference."""
        with self._lock:
            for j, h in enumerate(hashes):
                if h in self._cache:
                    self._cache.move_to_end(h)
                    continue
                p = phys[j]
                if self._ref[p] <= 0:
                    raise ValueError(f"commit of free block {p}")
                parent = hashes[j - 1] if j else None
                ent = _CacheEntry(p, parent)
                self._ref[p] += 1
                self._cache[h] = ent
                if parent is not None:
                    pent = self._cache.get(parent)
                    if pent is not None:
                        pent.kids += 1

    def _evict_one_locked(self) -> bool:
        """Evict the LRU cache LEAF whose block is otherwise unused
        (refcount == 1, i.e. only the cache holds it). Leaf-first —
        an entry with cached kids is load-bearing for longer chains —
        and never a block an active stream still reads."""
        victim = None
        for h, ent in self._cache.items():        # LRU -> MRU order
            if ent.kids == 0 and self._ref[ent.phys] == 1:
                victim = h
                break
        if victim is None:
            return False
        ent = self._cache.pop(victim)
        if ent.parent is not None:
            pent = self._cache.get(ent.parent)
            if pent is not None:
                pent.kids -= 1
        self._ref[ent.phys] -= 1
        if self._ref[ent.phys] == 0:
            self._free.append(ent.phys)
        self.stats.inc("prefix_evictions")
        return True

    # -- introspection -------------------------------------------------

    def stats_dict(self) -> Dict[str, float]:
        with self._lock:
            free = len(self._free)
            cached = len(self._cache)
        snap = self.stats.snapshot()
        hits = snap.get("prefix_hits", 0)
        misses = snap.get("prefix_misses", 0)
        total = hits + misses
        return {"blocks_free": free,
                "blocks_used": self.n_blocks - free,
                "blocks_cached": cached,
                "prefix_hits": hits,
                "prefix_misses": misses,
                "prefix_evictions": snap.get("prefix_evictions", 0),
                "alloc_failures": snap.get("alloc_failures", 0),
                "prefix_hit_ratio": (hits / total) if total else 0.0}
