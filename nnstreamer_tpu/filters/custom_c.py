"""framework=custom — native .so custom filters over the C ABI.

≙ gst/nnstreamer/tensor_filter/tensor_filter_custom.c loading
NNStreamer_custom_class from a user .so (dlopen in the subplugin loader,
nnstreamer_subplugin.c:116-134). Our ABI is csrc/nns_custom.h; the .so
exports ``nns_custom_get()``. model=/path/to/filter.so.
"""
from __future__ import annotations

import ctypes
from typing import Any, List, Optional, Sequence

import numpy as np

from ..native.lib import (NnsTensorInfo, NnsTensorsInfo, RANK_LIMIT,
                          TENSOR_LIMIT)
from ..tensors.info import TensorInfo, TensorsInfo
from ..tensors.types import TensorType
from .base import FilterFramework, FilterProperties
from .registry import register_filter

# ctypes mirror of nns_custom_filter (csrc/nns_custom.h)
_INIT = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p)
_EXIT = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_GETDIM = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                           ctypes.POINTER(NnsTensorsInfo))
_SETDIM = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                           ctypes.POINTER(NnsTensorsInfo),
                           ctypes.POINTER(NnsTensorsInfo))
_INVOKE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                           ctypes.POINTER(NnsTensorsInfo),
                           ctypes.POINTER(ctypes.c_void_p),
                           ctypes.POINTER(NnsTensorsInfo),
                           ctypes.POINTER(ctypes.c_void_p))


class _CustomFilterStruct(ctypes.Structure):
    _fields_ = [("init", _INIT), ("exit", _EXIT),
                ("get_input_dim", _GETDIM), ("get_output_dim", _GETDIM),
                ("set_input_dim", _SETDIM), ("invoke", _INVOKE)]


# ordinals shared with csrc/nns_custom.h nns_tensor_type
_TYPE_ORDER = [TensorType.INT32, TensorType.UINT32, TensorType.INT16,
               TensorType.UINT16, TensorType.INT8, TensorType.UINT8,
               TensorType.FLOAT64, TensorType.FLOAT32, TensorType.INT64,
               TensorType.UINT64, TensorType.FLOAT16]


def _to_c_infos(infos: TensorsInfo) -> NnsTensorsInfo:
    if len(infos) > TENSOR_LIMIT:
        raise ValueError(
            f"custom-C ABI supports at most {TENSOR_LIMIT} tensors, "
            f"got {len(infos)} (nns_custom.h NNS_TENSOR_LIMIT)")
    out = NnsTensorsInfo()
    out.num = len(infos)
    for i, info in enumerate(infos):
        ci = out.info[i]
        dims = list(reversed(info.shape))  # innermost-first
        if len(dims) > RANK_LIMIT:
            raise ValueError(
                f"custom-C ABI supports rank <= {RANK_LIMIT}, got "
                f"{len(dims)} (nns_custom.h NNS_RANK_LIMIT)")
        ci.rank = len(dims)
        for d in range(RANK_LIMIT):
            ci.dims[d] = dims[d] if d < len(dims) else 1
        ci.type = _TYPE_ORDER.index(info.type)
    return out


def _from_c_infos(c: NnsTensorsInfo) -> TensorsInfo:
    infos = TensorsInfo()
    for i in range(c.num):
        ci = c.info[i]
        shape = tuple(reversed([ci.dims[d] for d in range(ci.rank)]))
        infos.append(TensorInfo(type=_TYPE_ORDER[ci.type], shape=shape))
    return infos


@register_filter
class CustomCFilter(FilterFramework):
    NAME = "custom"
    EXTENSIONS = (".so",)

    def __init__(self):
        self._dll = None
        self._ops: Optional[_CustomFilterStruct] = None
        self._priv = None
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None

    def open(self, props: FilterProperties) -> None:
        from ..utils.conf import conf
        # bare names resolve through the configured customfilters search
        # dirs (≙ [filter] customfilters / NNSTREAMER_CUSTOMFILTERS)
        path = conf.resolve_custom_filter(props.model_files[0])
        self._dll = ctypes.CDLL(path)
        get = self._dll.nns_custom_get
        get.restype = ctypes.POINTER(_CustomFilterStruct)
        self._ops = get().contents
        self._priv = self._ops.init(
            (props.custom_properties or "").encode())
        if not self._priv:
            raise RuntimeError(f"custom filter {path}: init failed")
        if self._ops.get_input_dim:
            cin, cout = NnsTensorsInfo(), NnsTensorsInfo()
            if self._ops.get_input_dim(self._priv, ctypes.byref(cin)) == 0 \
                    and cin.num:
                self._in_info = _from_c_infos(cin)
            if self._ops.get_output_dim and \
                    self._ops.get_output_dim(self._priv,
                                             ctypes.byref(cout)) == 0 \
                    and cout.num:
                self._out_info = _from_c_infos(cout)
        if props.input_info is not None and self._out_info is None:
            self.set_input_info(props.input_info)

    def close(self) -> None:
        if self._ops is not None and self._priv:
            self._ops.exit(self._priv)
            self._priv = None
        self._ops = None
        self._dll = None

    def get_model_info(self):
        return self._in_info, self._out_info

    def set_input_info(self, info: TensorsInfo) -> Optional[TensorsInfo]:
        if not self._ops.set_input_dim:
            return None
        cin = _to_c_infos(info)
        cout = NnsTensorsInfo()
        if self._ops.set_input_dim(self._priv, ctypes.byref(cin),
                                   ctypes.byref(cout)) != 0:
            raise RuntimeError("custom filter: set_input_dim failed")
        self._in_info = info.copy()
        self._out_info = _from_c_infos(cout)
        return self._out_info

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        arrays = [np.ascontiguousarray(np.asarray(x)) for x in inputs]
        in_infos = TensorsInfo(
            TensorInfo(type=TensorType.from_dtype(a.dtype), shape=a.shape)
            for a in arrays)
        if self._out_info is None:
            self.set_input_info(in_infos)
        cin = _to_c_infos(in_infos)
        cout = _to_c_infos(self._out_info)
        outs = [np.empty(i.shape, i.type.np_dtype) for i in self._out_info]
        in_ptrs = (ctypes.c_void_p * len(arrays))(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
        out_ptrs = (ctypes.c_void_p * len(outs))(
            *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs])
        rc = self._ops.invoke(self._priv, ctypes.byref(cin), in_ptrs,
                              ctypes.byref(cout), out_ptrs)
        if rc > 0:
            return []  # drop frame, keep pipeline (ref: invoke result >0)
        if rc < 0:
            raise RuntimeError(f"custom filter invoke failed ({rc})")
        return list(outs)
