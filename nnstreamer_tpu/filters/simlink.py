"""framework=simlink — deterministic slow-link queueing model.

A filter backend that behaves, timing-wise, like a model served over a
remote-attached chip: every frame pays a link round trip (``rtt``) plus
a serial on-chip service time (``svc``). The compute itself is a
trivial deterministic affine map (``y = 2x + 1`` in the input dtype),
so sync and overlapped runs are byte-comparable.

It exists for the bench's ``async_overlap`` row and the overlap tests:
with it the queueing math is exact —

  * synchronous invoke:   fps ≈ 1 / (rtt + svc)      (≈ 1/RTT collapse)
  * K-frame window:       fps ≈ min(K / rtt, 1 / svc)

because :meth:`dispatch` returns immediately (the frame is "on the
link") and :meth:`complete` waits out THIS frame's absolute deadline
(RTT legs overlap across frames) then serializes ``svc`` on the
completer (the chip runs one program at a time). Doubling ``rtt``
mid-run via :func:`set_weather` leaves the windowed pipeline's
throughput at min(K/rtt, 1/svc) while the sync pipeline halves — the
weather-resilience verdict the bench row checks.

Custom properties (``custom=rtt:60,svc:5,fail-every:0``):
  * ``rtt``        link round trip per frame, ms (default 0)
  * ``svc``        serial service time per frame, ms (default 0)
  * ``svc-row``    serial service time PER BATCH ROW, ms (default 0) —
                   with it a stacked batch of R rows costs
                   ``svc + svc-row * ceil(R / dp)``
  * ``mesh``       a ``DxSxT`` spec whose data-parallel degree divides
                   the per-row service across simulated chips (default
                   dp=1). The mesh half of the ``sharded_serve`` bench
                   row: rows of one batch run dp-wide, so batch service
                   scales as ceil(R/dp) — the deterministic stand-in
                   for a real pod's batch-major fan-out (the 1-core CI
                   host cannot show a real dp speedup)
  * ``fail-every`` raise on every Nth frame's completion (0 = never) —
                   chaos hook for breaker/shed accounting with frames
                   in flight
"""
from __future__ import annotations

import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..tensors.info import TensorsInfo
from .base import (FilterFramework, FilterProperties,
                   parse_custom_properties as _parse_custom)
from .registry import register_filter

# live link weather, keyed by override scope (None = all simlink
# instances). Written only from the bench/test (API) thread via
# set_weather and read per frame — single-writer plain store.
_weather_rtt_ms: Optional[float] = None


def set_weather(rtt_ms: Optional[float]) -> None:
    """Override every simlink instance's RTT mid-run (None = back to
    each instance's configured value). The bench's weather-doubling
    knob."""
    global _weather_rtt_ms
    _weather_rtt_ms = None if rtt_ms is None else float(rtt_ms)


@register_filter
class SimLinkFilter(FilterFramework):
    """framework=simlink: remote-link timing model, deterministic math."""

    NAME = "simlink"
    SUPPORTS_BATCH = True
    SUPPORTS_DISPATCH = True

    def __init__(self):
        self._rtt_s = 0.0
        self._svc_s = 0.0
        self._svc_row_s = 0.0
        self._dp = 1
        self._fail_every = 0
        self._in_info: Optional[TensorsInfo] = None
        # frame counter for fail-every: dispatched from the chain
        # thread only, but a lock keeps it exact if a future caller
        # dispatches from several threads
        self._lock = threading.Lock()
        self._n = 0

    def open(self, props: FilterProperties) -> None:
        opts = _parse_custom(props.custom_properties)
        self._rtt_s = float(opts.get("rtt", 0.0)) / 1e3
        self._svc_s = float(opts.get("svc", 0.0)) / 1e3
        self._svc_row_s = float(opts.get("svc-row", 0.0)) / 1e3
        self._dp = 1
        if "mesh" in opts:
            from ..parallel.mesh import spec_dp
            self._dp = max(1, spec_dp(str(opts["mesh"])))
        self._fail_every = int(opts.get("fail-every", 0))
        self._in_info = props.input_info

    def set_input_info(self, info: TensorsInfo):
        # push-path negotiation: output mirrors the input exactly
        self._in_info = info
        return info

    def get_model_info(self):
        return self._in_info, self._in_info

    def _rtt(self) -> float:
        w = _weather_rtt_ms
        return self._rtt_s if w is None else w / 1e3

    @staticmethod
    def _compute(inputs: Sequence[Any]) -> List[Any]:
        # same-dtype affine map: wraps identically for integer dtypes on
        # every path, so sync/async byte parity is exact
        return [(np.asarray(x) * 2 + 1).astype(np.asarray(x).dtype)
                for x in inputs]

    def _svc(self, inputs: Sequence[Any]) -> float:
        """Per-frame service time: the flat ``svc`` plus the per-row
        cost with the rows of one stacked batch spread dp-wide —
        ``svc + svc-row * ceil(rows / dp)``, rows = leading dim."""
        svc = self._svc_s
        if self._svc_row_s > 0.0 and len(inputs):
            x = np.asarray(inputs[0])
            rows = int(x.shape[0]) if x.ndim else 1
            svc += self._svc_row_s * (-(-rows // self._dp))
        return svc

    def _tick(self) -> int:
        with self._lock:
            self._n += 1
            return self._n

    def _maybe_fail(self, n: int) -> None:
        if self._fail_every > 0 and n % self._fail_every == 0:
            raise RuntimeError(f"simlink: injected failure on frame {n}")

    # -- synchronous path: the full serial cost per frame -----------------
    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        n = self._tick()
        time.sleep(self._rtt() + self._svc(inputs))
        self._maybe_fail(n)
        return self._compute(inputs)

    # -- overlapped path --------------------------------------------------
    def dispatch(self, inputs: Sequence[Any], donate: bool = False) -> Any:
        """The frame goes "onto the link" and the chain thread returns:
        the handle carries the absolute arrival deadline, so RTT legs of
        consecutive in-flight frames overlap in wall time."""
        n = self._tick()
        return (list(inputs), time.monotonic() + self._rtt(), n)

    def complete(self, handle: Any) -> List[Any]:
        inputs, deadline, n = handle
        # wait out THIS frame's link deadline (overlapped across frames),
        # then pay the service time serially — the completer thread is
        # the stand-in for the chip running one program at a time
        left = deadline - time.monotonic()
        if left > 0:
            time.sleep(left)
        svc = self._svc(inputs)
        if svc > 0:
            time.sleep(svc)
        self._maybe_fail(n)
        return self._compute(inputs)
