"""In-process custom filters.

``custom-easy``: register a python callable + I/O info at runtime and use it
as ``framework=custom-easy model=<name>``
(≙ NNS_custom_easy_register, ref: gst/nnstreamer/tensor_filter/
tensor_filter_custom_easy.c and include/tensor_filter_custom_easy.h).

These are also the framework's test fixtures, standing in for real models
exactly like the reference's custom_example_passthrough/scaler/average
subplugins (SURVEY.md §4 fixtures).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..tensors.info import TensorsInfo
from .base import FilterFramework, FilterProperties
from .registry import register_filter

_CUSTOM_EASY: Dict[str, Tuple[Callable, Optional[TensorsInfo], Optional[TensorsInfo]]] = {}
_LOCK = threading.Lock()


def register_custom_easy(name: str, fn: Callable[..., Any],
                         in_info: Optional[TensorsInfo] = None,
                         out_info: Optional[TensorsInfo] = None) -> None:
    """fn(*input_arrays) -> array | list of arrays."""
    with _LOCK:
        _CUSTOM_EASY[name] = (fn, in_info, out_info)


def unregister_custom_easy(name: str) -> bool:
    with _LOCK:
        return _CUSTOM_EASY.pop(name, None) is not None


@register_filter
class CustomEasyFilter(FilterFramework):
    NAME = "custom-easy"
    EXTENSIONS = ()

    def __init__(self):
        self._fn: Optional[Callable] = None
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None

    def open(self, props: FilterProperties) -> None:
        name = props.model_files[0] if props.model_files else ""
        with _LOCK:
            if name not in _CUSTOM_EASY:
                raise ValueError(f"custom-easy model {name!r} not registered; "
                                 f"known: {sorted(_CUSTOM_EASY)}")
            self._fn, self._in_info, self._out_info = _CUSTOM_EASY[name]

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        out = self._fn(*inputs)
        return list(out) if isinstance(out, (list, tuple)) else [out]

    def get_model_info(self):
        return self._in_info, self._out_info


@register_filter
class Python3Filter(FilterFramework):
    """framework=python3 model=<script.py>: the user script defines
    ``invoke(inputs) -> list`` and optionally ``get_input_info`` /
    ``get_output_info`` / ``set_input_info``
    (≙ tensor_filter_python3.cc — embedded CPython; here it IS python)."""

    NAME = "python3"
    EXTENSIONS = (".py",)

    def __init__(self):
        self._ns: Dict[str, Any] = {}

    def open(self, props: FilterProperties) -> None:
        path = props.model_files[0]
        with open(path) as f:
            code = f.read()
        ns: Dict[str, Any] = {"__file__": path,
                              "custom_properties": props.custom_properties}
        exec(compile(code, path, "exec"), ns)  # noqa: S102 - user script by design
        if "invoke" not in ns:
            raise ValueError(f"{path}: python3 filter must define invoke()")
        self._ns = ns

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        out = self._ns["invoke"](list(inputs))
        return list(out) if isinstance(out, (list, tuple)) else [out]

    def get_model_info(self):
        gi = self._ns.get("get_input_info")
        go = self._ns.get("get_output_info")
        return (gi() if gi else None), (go() if go else None)

    def set_input_info(self, info: TensorsInfo) -> Optional[TensorsInfo]:
        si = self._ns.get("set_input_info")
        return si(info) if si else None
