"""onnxruntime interop backend: .onnx models on the XLA path.

≙ ext/nnstreamer/tensor_filter/tensor_filter_onnxruntime.cc (478 LoC
around the ORT C++ session). The model is imported once
(interop/onnx.py) into a jittable function compiled by XLA — same
convergence story as the tensorflow-lite backend.
"""
from __future__ import annotations

from .interop_base import ImportedModelFilter
from .registry import register_alias, register_filter


def _load(path: str):
    from ..interop import onnx
    return onnx.load(path)


@register_filter
class ONNXFilter(ImportedModelFilter):
    NAME = "onnxruntime"
    EXTENSIONS = (".onnx",)
    _load = staticmethod(_load)


register_alias("onnx", "onnxruntime")
