"""tensorflow backend: frozen GraphDef (.pb) models on the XLA path.

≙ ext/nnstreamer/tensor_filter/tensor_filter_tensorflow.cc (TF C API
session). The graph imports once (interop/tf_graphdef.py) into a
jittable function — no tensorflow dependency, same engine as every
other backend.
"""
from __future__ import annotations

from .interop_base import ImportedModelFilter
from .registry import register_filter


def _load(path: str):
    from ..interop import tf_graphdef
    return tf_graphdef.load(path)


@register_filter
class TFGraphFilter(ImportedModelFilter):
    NAME = "tensorflow"
    EXTENSIONS = (".pb",)
    _load = staticmethod(_load)
