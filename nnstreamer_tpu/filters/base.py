"""FilterFramework: the filter-backend subplugin ABI.

The Python analog of GstTensorFilterFramework **v1**
(ref: gst/nnstreamer/include/nnstreamer_plugin_api_filter.h:399-475 —
open/close/invoke/getFrameworkInfo/getModelInfo/eventHandler), with the
reference's event vocabulary (DESTROY_NOTIFY, RELOAD_MODEL, CUSTOM_PROP,
SET_INPUT_PROP, SET_OUTPUT_PROP, SET_ACCELERATOR, SUSPEND, RESUME) and
async output dispatch for generative models
(ref: nnstreamer_filter_dispatch_output_async, :613).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..tensors.info import TensorsInfo


def parse_custom_properties(s: str) -> Dict[str, str]:
    """``k:v,k:v`` custom option string (the reference's custom-prop
    grammar, e.g. ``custom=mesh:2x1x4,rules:gpt``); a bare key maps to
    ``"true"``."""
    out: Dict[str, str] = {}
    for part in (s or "").split(","):
        part = part.strip()
        if ":" in part:
            k, v = part.split(":", 1)
            out[k.strip()] = v.strip()
        elif part:
            out[part] = "true"
    return out


class InvokeDrop(Exception):
    """Raised by a backend's ``invoke`` to signal "drop this frame, keep
    the pipeline" (≙ invoke result > 0, tensor_filter.c:961-963). Any
    other exception from invoke is counted as an invoke *error*; both
    drop the frame rather than killing the pipeline."""


class FilterEvent(enum.Enum):
    """(ref: event_ops enum, nnstreamer_plugin_api_filter.h:205-217)"""

    DESTROY_NOTIFY = "destroy_notify"
    RELOAD_MODEL = "reload_model"
    CUSTOM_PROP = "custom_prop"
    SET_INPUT_PROP = "set_input_prop"
    SET_OUTPUT_PROP = "set_output_prop"
    SET_ACCELERATOR = "set_accelerator"
    CHECK_HW_AVAILABILITY = "check_hw_availability"
    SUSPEND = "suspend"
    RESUME = "resume"


class Accelerator(enum.Enum):
    """(ref: accl_hw enum, nnstreamer_plugin_api_filter.h:80-102).
    On this framework DEFAULT means the JAX default device (TPU)."""

    NONE = "none"
    DEFAULT = "default"
    CPU = "cpu"
    TPU = "tpu"
    GPU = "gpu"

    @classmethod
    def parse(cls, s: str) -> List["Accelerator"]:
        """Parse reference-style accelerator strings: "true:tpu.cpu"
        (ref: parse_accl_hw, nnstreamer_plugin_api_filter.h:529-550)."""
        s = (s or "").strip()
        if not s or s.lower() in ("false", "none"):
            return [cls.NONE]
        if ":" in s:
            _, rest = s.split(":", 1)
        elif s.lower() in ("true", "auto"):
            rest = "default"
        else:
            rest = s
        out = []
        for part in rest.replace(",", ".").split("."):
            part = part.strip().lower()
            if not part:
                continue
            try:
                out.append(cls(part))
            except ValueError:
                out.append(cls.DEFAULT)
        return out or [cls.DEFAULT]


@dataclasses.dataclass
class FilterProperties:
    """Per-instance filter configuration handed to the framework
    (ref: GstTensorFilterProperties, nnstreamer_plugin_api_filter.h:112-144)."""

    framework: str = ""
    model_files: Tuple[str, ...] = ()
    input_info: Optional[TensorsInfo] = None
    output_info: Optional[TensorsInfo] = None
    accelerators: Tuple[Accelerator, ...] = (Accelerator.DEFAULT,)
    custom_properties: str = ""
    invoke_dynamic: bool = False   # output shape may vary per invoke
    invoke_async: bool = False     # N outputs per input via dispatcher
    shared_key: Optional[str] = None
    latency_report: bool = False


class FilterFramework:
    """Backend subplugin base class (≙ GstTensorFilterFramework v1).

    Lifecycle: ``open`` loads the model, ``invoke`` runs it, ``close``
    releases. ``invoke`` takes/returns a list of arrays (host ndarrays or
    device jax.Arrays — TPU backends keep everything device-resident).
    """

    NAME = ""
    # framework auto-detect: model-file extensions this backend claims
    # (ref: gst_tensor_filter_detect_framework, tensor_filter_common.c:1174)
    EXTENSIONS: Tuple[str, ...] = ()
    AVAILABLE = True
    # True when invoke() accepts inputs with one extra leading batch dim
    # (the element then negotiates aggregator-stacked streams); backends
    # that lower to a fixed model shape must leave this False
    SUPPORTS_BATCH = False
    # True when the backend can split invoke into a non-blocking
    # dispatch() and a blocking complete() — what the element's K-frame
    # in-flight window (in-flight property) is built on. Backends whose
    # invoke is inherently synchronous leave this False; the element
    # then ignores the window and stays synchronous.
    SUPPORTS_DISPATCH = False

    def open(self, props: FilterProperties) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        raise NotImplementedError

    # overlapped execution -------------------------------------------------
    def dispatch(self, inputs: Sequence[Any], donate: bool = False
                 ) -> Any:
        """Enqueue one frame's device program WITHOUT waiting for the
        results; returns an opaque in-flight handle for
        :meth:`complete`. ``donate`` permits input/output buffer
        aliasing for inputs the backend itself staged (platform
        permitting). The default implementation degrades to a
        synchronous invoke — the handle IS the outputs — so a window of
        K over a non-async backend is merely useless, never wrong."""
        return self.invoke(inputs)

    def complete(self, handle: Any) -> List[Any]:
        """Block until a dispatched frame's outputs are materialized
        enough to hand downstream; raises if the device program failed.
        Called from the element's completer thread — implementations
        must be safe to run concurrently with :meth:`dispatch`."""
        return handle

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        """(input_info, output_info); either may be None if the backend
        derives it from the negotiated caps (SET_INPUT_PROP path)."""
        return None, None

    def set_input_info(self, info: TensorsInfo) -> Optional[TensorsInfo]:
        """Negotiation push-path: given input info, return output info
        (≙ getModelInfo SET_INPUT_INFO, nnstreamer_plugin_api_filter.h:439)."""
        return None

    def handle_event(self, event: FilterEvent, data: Optional[dict] = None) -> bool:
        """Return True if handled. RELOAD_MODEL/SUSPEND/RESUME arrive here."""
        return False

    # async generative path -----------------------------------------------
    def set_async_dispatcher(
            self, dispatch: Callable[..., None]) -> None:
        """Element installs a callback; an async backend calls it once per
        produced output frame (≙ nnstreamer_filter_dispatch_output_async).
        The callback signature is ``dispatch(outputs, ctx=None)`` — the
        backend hands back the opaque ``ctx`` it was given at
        ``invoke_async`` time so the element can attribute each output
        frame to its originating input (the reference passes the
        GstTensorFilter handle + per-invoke data the same way); with
        several invokes in flight, omitting ctx mis-stamps frames."""
        self._dispatch = dispatch

    def invoke_async(self, inputs: Sequence[Any], ctx: Any = None) -> None:
        """1-in/N-out invoke; outputs flow through the dispatcher, each
        carrying ``ctx`` back to the element."""
        raise NotImplementedError
