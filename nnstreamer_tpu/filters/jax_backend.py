"""The JAX/XLA TPU filter backend — this framework's native inference engine.

Where the reference fans out to 30 vendor SDK subplugins
(ref: ext/nnstreamer/tensor_filter/*, SURVEY.md §2.5), the TPU-native
design collapses them into one backend: a model resolves to a pure
``apply_fn(params, *inputs)``, params live in HBM, and invoke dispatches a
**cached jax.jit executable per input signature** (≙ the reference's
fw->invoke hot call, tensor_filter.c:1227, with the EdgeTPU/TensorRT
engine-cache idea done the XLA way).

Model URIs accepted by the ``model`` property:
  * ``zoo://<name>?k=v&...``  — in-repo model zoo (flax), deterministic
    random init unless ``params_dir=<orbax dir>`` is given.
  * ``<file>.jaxm.py``        — a python module defining
    ``get_model() -> (apply_fn, params, input_info, output_info)``.
  * ``<dir>`` with orbax checkpoint + ``model.json`` zoo spec.

Outputs stay device-resident (jax.Array) so chained elements keep HBM
residency; they materialize only at host boundaries.

**Mesh mode** (multi-chip invoke): ``custom=mesh:<dp>x<sp>x<tp>`` (or
``mesh:auto``) builds a `jax.sharding.Mesh`, places params by the
``rules:`` table (``gpt`` = Megatron TP from parallel/sharding.py;
default = replicate), and shards the input batch over the ``data`` axis,
so one invoke fans out over every chip with XLA inserting the ICI
collectives. This is the TPU-native answer to the reference's
among-device stream fan-out (ref: tensor_query/README.md:5-27 — there,
frames are RPC'd to other devices; here the mesh IS the device pool).
"""
from __future__ import annotations

import json
import os
import threading
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..tensors.info import TensorsInfo
from ..utils.log import logger
from .base import (Accelerator, FilterEvent, FilterFramework,
                   FilterProperties,
                   parse_custom_properties as _parse_custom)
from .registry import register_filter


def _device_for(accelerators: Sequence[Accelerator]):
    import jax
    for acc in accelerators:
        if acc in (Accelerator.CPU, Accelerator.NONE):
            # accelerator=false / cpu is an explicit opt-out of the TPU
            try:
                return jax.devices("cpu")[0]
            except RuntimeError:
                continue
        return jax.devices()[0]
    return jax.devices()[0]


@register_filter
class JaxFilter(FilterFramework):
    """framework=jax (aliases: jax-tpu). The flagship backend."""

    NAME = "jax"
    EXTENSIONS = (".py", ".jaxm", ".msgpack")
    SUPPORTS_BATCH = True  # apply fns broadcast over a leading batch dim
    # JAX dispatch is async on every backend: dispatch() below returns
    # as soon as the executable is enqueued, complete() blocks — the
    # split the element's in-flight window is built on
    SUPPORTS_DISPATCH = True

    # platforms where jax.jit honors donate_argnums (CPU logs a warning
    # per donated arg and ignores it — gate rather than spam)
    _DONATION_PLATFORMS = ("tpu", "gpu")

    def __init__(self):
        self._apply: Optional[Callable] = None
        self._params: Any = None
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None
        self._jit_cache: Dict[Tuple, Any] = {}
        self._device = None
        self._mesh = None
        self._param_sharding = None
        self._props: Optional[FilterProperties] = None
        self._lock = threading.Lock()
        self._suspended = False
        # monotonically counts jit-cache misses (actual trace+compile),
        # warmup and prewarm included — the element baselines it at
        # start() so its jit_recompiles stat counts only frame-path
        # compiles (the jit-stability gate pins those to zero)
        self.compile_count = 0
        # persistent compile cache identity (fleet/cache.py): model URI
        # + mesh spec — donation variants key per entry, not per model
        self._cache_key = ""

    # -- lifecycle --------------------------------------------------------
    def open(self, props: FilterProperties) -> None:
        import jax
        self._props = props
        opts = _parse_custom(props.custom_properties)
        model = props.model_files[0] if props.model_files else ""
        self._load_model(model, props)
        if "mesh" in opts:
            from ..parallel.mesh import mesh_from_spec
            from ..parallel.sharding import named_sharding_tree, rules_by_name
            self._mesh = mesh_from_spec(opts["mesh"])
            rules = rules_by_name(opts.get("rules", ""))
            self._param_sharding = named_sharding_tree(
                self._params, rules, self._mesh)
            if self._params is not None:
                self._params = jax.device_put(self._params,
                                              self._param_sharding)
            logger.info("jax filter opened model=%s on mesh %s", model,
                        dict(self._mesh.shape))
        else:
            self._device = _device_for(props.accelerators)
            if self._params is not None:
                self._params = jax.device_put(self._params, self._device)
            logger.info("jax filter opened model=%s on %s", model,
                        self._device)
        self._cache_key = f"{model}|mesh={opts.get('mesh', '')}"
        self._prewarm_from_cache()

    def _prewarm_from_cache(self) -> None:
        """Replay every signature this model compiled in previous lives
        (fleet/cache.py): the jit cache is hot BEFORE the first frame
        arrives — and before a serve pipeline REGISTERs on the broker —
        so a resurrected or scaled-up replica's first-frame latency is
        steady-state, not compile-bound."""
        from ..fleet import cache as compile_cache
        cc = compile_cache.active()
        if cc is None or self._apply is None:
            return
        cc.enable_xla_cache()
        import jax
        warmed = 0
        for sig, donate in cc.signatures("jax", self._cache_key):
            if donate and (self._device is None or self._device.platform
                           not in self._DONATION_PLATFORMS):
                donate = ()  # recorded on a donating platform; not here
            try:
                xs = [np.zeros(shape, dtype) for shape, dtype in sig]
                if self._mesh is not None:
                    xs = self._place_inputs(xs)
                else:
                    xs = [jax.device_put(x, self._device) for x in xs]
                out = self._executable(sig, donate)(self._params, *xs)
                jax.block_until_ready(out)
                warmed += 1
            except Exception as exc:
                # a stale signature (model shape change across versions)
                # only costs its own replay, never the open
                logger.info("jax filter: cached signature %s skipped: %s",
                            sig, exc)
        if warmed:
            logger.info("jax filter: prewarmed %d signature(s) for %s",
                        warmed, self._cache_key)

    def _load_model(self, model: str, props: FilterProperties) -> None:
        if model.startswith("zoo://"):
            from ..models import zoo
            parsed = urllib.parse.urlparse(model)
            kwargs = {k: v[0] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
            name = parsed.netloc or parsed.path.lstrip("/")
            (self._apply, self._params,
             self._in_info, self._out_info) = zoo.build(name, **kwargs)
        elif model.endswith(".py"):
            ns: Dict[str, Any] = {}
            with open(model) as f:
                code = f.read()
            exec(compile(code, model, "exec"), ns)  # noqa: S102 - user script, like python3 subplugin
            if "get_model" not in ns:
                raise ValueError(f"{model}: must define get_model()")
            (self._apply, self._params,
             self._in_info, self._out_info) = ns["get_model"]()
        elif os.path.isdir(model) and os.path.exists(
                os.path.join(model, "model.json")):
            with open(os.path.join(model, "model.json")) as f:
                spec = json.load(f)
            from ..models import zoo
            (self._apply, self._params,
             self._in_info, self._out_info) = zoo.build(
                spec["name"], params_dir=model, **spec.get("kwargs", {}))
        else:
            raise ValueError(f"jax backend cannot load model {model!r}")

    def close(self) -> None:
        self._apply = None
        self._params = None
        self._jit_cache.clear()

    # -- info -------------------------------------------------------------
    def get_model_info(self):
        return self._in_info, self._out_info

    # -- invoke -----------------------------------------------------------
    def _executable(self, sig: Tuple,
                    donate_idx: Tuple[int, ...] = ()) -> Callable:
        """One compiled executable per input signature (shape/dtype tuple).
        Recompile-on-new-signature is the static-shape answer to dynamic
        models (SURVEY.md §7 hard part (a)). ``donate_idx`` (1-based:
        arg 0 is params, which are NEVER donated) selects inputs whose
        device buffers XLA may alias into the outputs; it is part of the
        cache key because donation changes the compiled program."""
        key = (sig, donate_idx) if donate_idx else sig
        exe = self._jit_cache.get(key)
        if exe is None:
            import jax
            fn = self._apply

            def call(params, *xs):
                return fn(params, *xs)

            exe = jax.jit(call, donate_argnums=donate_idx) if donate_idx \
                else jax.jit(call)
            self._jit_cache[key] = exe
            self.compile_count += 1
            self._record_signature(sig, donate_idx)
        return exe

    def _record_signature(self, sig: Tuple,
                          donate_idx: Tuple[int, ...]) -> None:
        """Persist a freshly-compiled signature so the NEXT incarnation
        of this model prewarms it (no-op without an installed cache)."""
        from ..fleet import cache as compile_cache
        cc = compile_cache.active()
        if cc is None or not self._cache_key:
            return
        try:
            cc.record("jax", self._cache_key, sig, donate_idx)
        except Exception as exc:  # cache IO is never allowed to fail serving
            logger.warning("jax filter: compile-cache record failed: %s",
                           exc)

    @property
    def mesh(self):
        """The live Mesh in mesh mode (None per-chip) — read by the
        fused-segment compiler, the in-flight window's per-mesh slot
        accounting, and trace.report()'s devices fields."""
        return self._mesh

    def _input_sharding(self, x):
        """Shard the batch (dim 0) over the ``data`` axis when divisible;
        replicate otherwise. XLA propagates from these committed inputs +
        the param shardings and inserts the ICI collectives."""
        from ..parallel.sharding import batch_sharding
        return batch_sharding(self._mesh, x.ndim,
                              x.shape[0] if x.ndim else 0)

    def _place_inputs(self, inputs):
        """Mesh placement of one invoke's inputs. An input the serve
        scheduler already committed with the wanted sharding passes
        through untouched — placement upstream (overlapped with
        batching) makes the dispatch leg here O(1), which keeps the
        windowed dispatch/complete latency split honest for sharded
        programs."""
        import jax
        xs = []
        for x in inputs:
            if isinstance(x, jax.Array):
                if x.sharding == self._input_sharding(x):
                    xs.append(x)
                    continue
                # device-resident but laid out differently: reshard on
                # device (device_put only reads shape/ndim on the host)
                xs.append(jax.device_put(x, self._input_sharding(x)))
            else:
                x = np.asarray(x)
                xs.append(jax.device_put(x, self._input_sharding(x)))
        return xs

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        import jax
        with self._lock:
            if self._suspended:
                self._resume()
            if self._mesh is not None:
                xs = self._place_inputs(inputs)
            else:
                # a mesh-committed upstream output (sharded filter or
                # serve placement) must collapse to this chip: jit
                # refuses mixed device sets otherwise
                xs = [x if isinstance(x, jax.Array)
                      and len(x.sharding.device_set) == 1 else
                      jax.device_put(x if isinstance(x, jax.Array)
                                     else np.asarray(x), self._device)
                      for x in inputs]
            sig = tuple((tuple(x.shape), str(x.dtype)) for x in xs)
            out = self._executable(sig)(self._params, *xs)
        if isinstance(out, (list, tuple)):
            return list(out)
        return [out]

    # -- overlapped execution ---------------------------------------------
    def dispatch(self, inputs: Sequence[Any], donate: bool = False) -> Any:
        """Enqueue one frame's executable and return the (still
        materializing) output arrays as the in-flight handle — JAX
        dispatch is async, so this returns as soon as XLA has the
        program queued; errors surface in :meth:`complete`.

        Donation: with ``donate=True`` the H2D staging buffers of inputs
        THIS call uploaded are donated to the executable
        (input/output aliasing — the double-buffered H2D leg reuses its
        staging buffer for the outputs instead of allocating fresh HBM
        per in-flight frame). Device-resident inputs are upstream-owned
        and never donated; params (arg 0) never either. Gated to
        platforms where XLA honors donation — CPU ignores it with a
        warning per arg."""
        import jax
        with self._lock:
            if self._suspended:
                self._resume()
            donate_idx: Tuple[int, ...] = ()
            if self._mesh is not None:
                xs = self._place_inputs(inputs)
            else:
                xs = []
                staged: List[int] = []
                for i, x in enumerate(inputs):
                    if isinstance(x, jax.Array):
                        if len(x.sharding.device_set) > 1:
                            # mesh-committed upstream output: collapse
                            # to this chip (upstream-owned, not donated)
                            x = jax.device_put(x, self._device)
                        xs.append(x)
                    else:
                        xs.append(jax.device_put(np.asarray(x),
                                                 self._device))
                        staged.append(i + 1)  # 1-based: arg 0 is params
                if donate and staged \
                        and self._device.platform in self._DONATION_PLATFORMS:
                    donate_idx = tuple(staged)
            sig = tuple((tuple(x.shape), str(x.dtype)) for x in xs)
            out = self._executable(sig, donate_idx)(self._params, *xs)
        return out

    def complete(self, handle: Any) -> List[Any]:
        """Block until a dispatched frame's outputs are on-device
        materialized (raises the deferred device error, if any). Takes
        no lock: runs on the completer thread concurrently with
        dispatch — block_until_ready only touches the arrays."""
        import jax
        out = jax.block_until_ready(handle)
        if isinstance(out, (list, tuple)):
            return list(out)
        return [out]

    # -- fusion -----------------------------------------------------------
    def traceable_fn(self) -> Optional[Callable]:
        """Pure ``fn(*inputs) -> outputs`` closure over the current
        apply/params, for the fusion compiler to inline into a larger
        jit program (fusion/segment.py). Params are captured by value:
        the closure stays valid across suspend/reload, it just keeps
        serving the params it was planned with.

        In mesh mode the closed-over params are mesh-committed
        jax.Arrays, so the fused program compiles over the mesh with
        XLA propagating the param shardings ("computation follows
        data"); the segment pins batch-major layout at each member
        boundary via its sharding constraints, so a fused run stays
        mesh-resident end to end."""
        with self._lock:
            if self._suspended:
                self._resume()
            apply_fn, params = self._apply, self._params
            if apply_fn is None:
                return None

        def fn(*xs):
            return apply_fn(params, *xs)

        return fn

    # -- events -----------------------------------------------------------
    def handle_event(self, event: FilterEvent, data=None) -> bool:
        if event == FilterEvent.CHECK_HW_AVAILABILITY:
            from ..utils.hw import is_available
            return is_available((data or {}).get("hw", "default"))
        if event == FilterEvent.RELOAD_MODEL:
            # Keep serving with old params while the new ones load
            # (≙ is-updatable reload, nnstreamer_plugin_api_filter.h:359-365)
            assert self._props is not None
            fresh = JaxFilter()
            fresh.open(self._props if data is None else
                       self._props.__class__(**{**self._props.__dict__, **data}))
            with self._lock:
                self._apply, self._params = fresh._apply, fresh._params
                self._in_info, self._out_info = fresh._in_info, fresh._out_info
                self._mesh = fresh._mesh
                self._param_sharding = fresh._param_sharding
                self._device = fresh._device
                self._jit_cache.clear()
            return True
        if event == FilterEvent.SUSPEND:
            # Drop HBM copies; reopen transparently on next invoke
            # (≙ suspend watchdog unload, tensor_filter.c:1078-1090)
            import jax
            with self._lock:
                self._params = jax.device_get(self._params)
                self._jit_cache.clear()
                self._suspended = True
            return True
        if event == FilterEvent.RESUME:
            with self._lock:
                self._resume()
            return True
        return False

    def _resume(self) -> None:
        import jax
        if self._suspended:
            self._params = jax.device_put(
                self._params, self._param_sharding if self._mesh is not None
                else self._device)
            self._suspended = False


from .registry import register_alias as _register_alias  # noqa: E402

_register_alias("jax-tpu", "jax")
_register_alias("flax", "jax")
