"""Shared lifecycle for interop (imported-model) filter backends.

tensorflow-lite and onnxruntime differ only in their importer; the
open/compile/invoke/suspend/reload machinery is identical, so it lives
here once. Subclasses set ``NAME``, ``EXTENSIONS``, and ``_load``.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..tensors.info import TensorsInfo
from ..utils.log import logger
from .base import FilterEvent, FilterFramework, FilterProperties
from .jax_backend import _device_for


class ImportedModelFilter(FilterFramework):
    """Backend whose model is imported to one jittable function with
    static input/output_info (interop/tflite.py, interop/onnx.py)."""

    #: importer: path -> object with .fn / .input_info / .output_info
    _load: Callable[[str], Any]

    def __init__(self):
        self._model = None
        self._jit: Any = None
        self._device = None
        self._props: Optional[FilterProperties] = None
        self._lock = threading.Lock()
        self._suspended = False

    # -- lifecycle --------------------------------------------------------
    def open(self, props: FilterProperties) -> None:
        self._props = props
        self._device = _device_for(props.accelerators)
        if not props.model_files:
            raise ValueError(f"{self.NAME} backend needs a model file")
        self._model = type(self)._load(props.model_files[0])
        self._compile()
        logger.info("%s backend imported %s (%d in, %d out) on %s",
                    self.NAME, props.model_files[0],
                    len(self._model.input_info),
                    len(self._model.output_info), self._device)

    def _compile(self) -> None:
        import jax
        self._jit = jax.jit(self._model.fn)

    def close(self) -> None:
        self._model = None
        self._jit = None

    # -- info -------------------------------------------------------------
    def get_model_info(self) -> Tuple[Optional[TensorsInfo],
                                      Optional[TensorsInfo]]:
        if self._model is None:
            return None, None
        return self._model.input_info, self._model.output_info

    # -- invoke -----------------------------------------------------------
    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        import jax
        with self._lock:
            if self._suspended:
                self._compile()
                self._suspended = False
            infos = self._model.input_info
            xs = []
            for x, info in zip(inputs, infos):
                if not isinstance(x, jax.Array):
                    x = jax.device_put(np.asarray(x), self._device)
                # pipeline buffers omit size-1 batch dims (3:224:224 vs
                # the model's [1,224,224,3]); reshape by element count
                if tuple(x.shape) != tuple(info.shape):
                    x = x.reshape(info.shape)
                xs.append(x)
            out = self._jit(*xs)
        return list(out)

    # -- events -----------------------------------------------------------
    def handle_event(self, event: FilterEvent, data=None) -> bool:
        if event == FilterEvent.RELOAD_MODEL:
            assert self._props is not None
            path = (data or {}).get("model_files",
                                    self._props.model_files)[0]
            fresh = type(self)._load(path)
            with self._lock:
                self._model = fresh
                self._compile()
            return True
        if event == FilterEvent.SUSPEND:
            with self._lock:
                # drop the compiled executable (weights are baked into the
                # XLA program; releasing it releases HBM)
                self._jit = None
                self._suspended = True
            return True
        if event == FilterEvent.RESUME:
            with self._lock:
                if self._suspended:
                    self._compile()
                    self._suspended = False
            return True
        return False
