"""Filter-backend subplugins (L5) and their registry (L2)."""
from . import (custom, custom_c, jax_backend, llm,  # noqa: F401
               onnx_backend, simlink, tf_backend, tflite_backend,
               torch_backend)  # (register built-in backends)
from .base import (Accelerator, FilterEvent, FilterFramework,
                   FilterProperties, InvokeDrop)
from .custom import register_custom_easy, unregister_custom_easy
from .registry import (all_filters, detect_framework, find_filter,
                       register_alias, register_filter, shared_model_get,
                       shared_model_insert, shared_model_release,
                       shared_model_replace)

__all__ = [
    "FilterFramework", "FilterProperties", "FilterEvent", "Accelerator",
    "InvokeDrop",
    "register_filter", "register_alias", "find_filter", "all_filters",
    "detect_framework", "register_custom_easy", "unregister_custom_easy",
    "shared_model_get", "shared_model_insert", "shared_model_release",
    "shared_model_replace",
]
