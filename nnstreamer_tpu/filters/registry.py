"""Filter-backend registry: registration, lookup, auto-detection, and the
shared-model table.

≙ the subplugin registry + framework auto-detection + shared model registry
(ref: gst/nnstreamer/nnstreamer_subplugin.c:47-137 register/get;
tensor_filter_common.c:1127-1227 extension-based detection with priority
lists; nnstreamer_plugin_api_filter.h:560-598 nnstreamer_filter_shared_model_*).

Instead of dlopen'd .so self-registration, backends register via
``@register_filter`` at import time; out-of-tree backends can use Python
entry points or plain imports. C custom filters load via ctypes
(filters/custom_c.py).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from ..utils.conf import conf
from ..utils.log import logger
from .base import FilterFramework

_FRAMEWORKS: Dict[str, Type[FilterFramework]] = {}
_ALIASES: Dict[str, str] = {}
_LOCK = threading.Lock()


def register_filter(cls: Type[FilterFramework]) -> Type[FilterFramework]:
    with _LOCK:
        _FRAMEWORKS[cls.NAME] = cls
    return cls


def register_alias(alias: str, target: str) -> None:
    """(≙ [filter-aliases] section of nnstreamer.ini)"""
    _ALIASES[alias] = target


def find_filter(name: str) -> Type[FilterFramework]:
    # runtime-registered aliases win over configured ([filter-aliases]) ones
    name = _ALIASES.get(name) or conf.filter_aliases().get(name, name)
    with _LOCK:
        if name not in _FRAMEWORKS:
            raise ValueError(
                f"unknown filter framework {name!r}; known: {sorted(_FRAMEWORKS)}")
        cls = _FRAMEWORKS[name]
    if not cls.AVAILABLE:
        raise ValueError(f"filter framework {name!r} is not available "
                         "(missing optional dependency)")
    return cls


def all_filters() -> List[str]:
    with _LOCK:
        return sorted(_FRAMEWORKS)


def detect_framework(model_files: Tuple[str, ...]) -> str:
    """Pick a framework from model file extension(s)
    (≙ gst_tensor_filter_detect_framework, tensor_filter_common.c:1174-1227)."""
    if not model_files:
        raise ValueError("cannot auto-detect framework without model files")
    ext = os.path.splitext(model_files[0])[1].lower()
    with _LOCK:
        candidates = [
            (name, cls) for name, cls in _FRAMEWORKS.items()
            if ext in cls.EXTENSIONS and cls.AVAILABLE]
    if not candidates:
        raise ValueError(f"no framework claims model extension {ext!r}")
    # priority from the config tiers: per-extension ini/env key, then the
    # global list, then built-in defaults (≙ framework_priority_tflite
    # etc., nnstreamer_conf.c / nnstreamer.ini.in:12-19)
    priority = conf.framework_priority(ext)
    candidates.sort(key=lambda kv: priority.index(kv[0])
                    if kv[0] in priority else len(priority))
    name = candidates[0][0]
    logger.info("auto-detected framework %s for %s", name, model_files[0])
    return name


# -- shared model registry -------------------------------------------------
# (≙ nnstreamer_filter_shared_model_get/insert/remove/replace,
#  nnstreamer_plugin_api_filter.h:560-598): instances with the same
#  shared-tensor-filter-key share one opened backend (one HBM copy of the
#  weights — on TPU this is the difference between N models and 1).

_SHARED: Dict[str, Tuple[FilterFramework, int]] = {}
_SHARED_LOCK = threading.Lock()


def shared_model_get(key: str) -> Optional[FilterFramework]:
    with _SHARED_LOCK:
        entry = _SHARED.get(key)
        if entry is None:
            return None
        fw, refs = entry
        _SHARED[key] = (fw, refs + 1)
        return fw


def shared_model_insert(key: str, fw: FilterFramework) -> FilterFramework:
    with _SHARED_LOCK:
        if key in _SHARED:
            existing, refs = _SHARED[key]
            _SHARED[key] = (existing, refs + 1)
            return existing
        _SHARED[key] = (fw, 1)
        return fw


def shared_model_release(key: str) -> bool:
    """Drop one ref; close and remove on last release. Returns True if the
    backend was closed."""
    with _SHARED_LOCK:
        if key not in _SHARED:
            return False
        fw, refs = _SHARED[key]
        if refs <= 1:
            del _SHARED[key]
            fw.close()
            return True
        _SHARED[key] = (fw, refs - 1)
        return False


def shared_model_replace(key: str, fw: FilterFramework) -> None:
    """Hot-swap the shared backend under the same key (≙ ..._replace)."""
    with _SHARED_LOCK:
        old = _SHARED.get(key)
        _SHARED[key] = (fw, old[1] if old else 1)
        if old is not None:
            old[0].close()
