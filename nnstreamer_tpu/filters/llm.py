"""LLM generative filter — async token streaming on the JAX decode loop.

≙ ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc: 1 prompt in,
N token frames out via the async dispatcher
(nnstreamer_filter_dispatch_output_async, tensor_filter.c:1099-1170).
Here generation is the KV-cache decode loop of models/transformer.py —
static shapes, one jitted decode step reused every token.

model accepts ``zoo://gpt?...`` (zoo spec) or a ``get_lm()`` python file
returning (params, cfg). custom properties (``custom=key:value,...``):
max_tokens, temperature (0 = greedy), top_k, top_p, seed, max_len,
n_parallel, chunk.

``n_parallel:M`` (M>1) turns on continuous-batching decode: up to M
concurrent prompts share ONE decode dispatch per token step (the
TPU-first answer to llamacpp's n_batch, tensor_filter_llamacpp.cc:267)
— prompts are prefetched into cache slots as they free up, so decode
dispatch count scales with max(stream depth), not streams x tokens.

Disaggregated serving options (see Documentation/llm.md):

* ``paged:true`` — back the scheduler with a block-granular KV pool
  (``block_size:N`` tokens/block, ``pool_blocks:N`` budget) instead of
  per-slot contiguous lanes: admission is token-budgeted, and with
  ``prefix_cache:true`` (default in paged mode) prompts whose
  block-aligned prefix chain is warm skip that part of prefill
  entirely. Emitted token streams are bit-identical to the contiguous
  path (the tests/test_llm_disagg.py parity gate).
* ``role:prefill|decode|both`` — phase split across replicas: a
  prefill replica runs only the prompt pass and ships the KV prefix to
  ``handoff:host:port`` over the negotiated KV_XFER link (edge/kv.py,
  ``kv_precision:none|bf16|fp16``); a decode replica (implies paged)
  listens on ``handoff_port:N`` (0 = ephemeral; see
  ``filter.handoff_port``) and folds shipped streams into its
  continuous-batching loop.
"""
from __future__ import annotations

import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..tensors.info import TensorsInfo
from ..utils.atomic import Counters
from ..utils.log import logger
from .base import (FilterFramework, FilterProperties,
                   parse_custom_properties as _parse_custom)
from .registry import register_alias, register_filter

# default shared-cache length in n_parallel mode. The batched cache is
# allocated ONCE (static shapes), so unlike the single-stream path the
# default cannot derive from each prompt's bucket; longer prompts need an
# explicit custom=max_len:N.
DEFAULT_BATCH_MAX_LEN = 128

_TRUE = ("1", "true", "yes", "on")


def _ctx_of(ctx: Any):
    """The TraceContext riding on a Buffer-shaped invoke ctx, if any
    (plain correlation tokens — ints, strings — carry none)."""
    try:
        from ..obs import context as _obs_ctx
        return _obs_ctx.ctx_of(ctx)
    except Exception:  # noqa: BLE001 — tracing is best-effort by design
        return None


class _PoolFull(Exception):
    """Paged admission backpressure: the KV pool cannot cover this
    stream right now — the scheduler requeues and retries as running
    streams release blocks."""


class _ContigBackend:
    """Per-slot contiguous cache lanes (decode_step_multi): every slot
    reserves a worst-case [max_len] lane, so occupancy is
    stream-counted. The pre-paging layout, kept as the parity oracle
    and for small deployments where the lane waste is irrelevant."""

    def __init__(self, filt: "LlmFilter", m: int, max_len: int):
        import jax.numpy as jnp

        self.f = filt
        self.max_len = max_len
        self.cache = filt._tfm.init_cache_multi(filt._cfg, batch=m,
                                                max_len=max_len)
        self.logits = jnp.zeros((m, filt._cfg.vocab), jnp.float32)

    def admit(self, slot: int, prompt: np.ndarray, budget: int) -> None:
        import jax.numpy as jnp

        l1, c1 = self.f._prefill_prompt(prompt, self.max_len)
        self.cache = self.f._insert(self.cache, c1,
                                    jnp.asarray(slot, jnp.int32))
        self.logits = self.logits.at[slot].set(l1[0])

    def admit_handoff(self, slot, prompt, kv, budget) -> None:
        raise ValueError("llm: the contiguous cache cannot adopt a KV "
                         "handoff; decode replicas need custom=paged:true")

    def step(self, tok, active_np) -> None:
        import jax.numpy as jnp

        self.logits, self.cache = self.f._decode_multi(
            self.f._params, self.cache, tok, jnp.asarray(active_np))

    def chunk(self, k: int, temperature: float, keys, active_np):
        import jax.numpy as jnp

        toks, self.logits, self.cache, keys = self.f._chunk_fn(
            k, temperature)(self.f._params, self.cache, self.logits,
                            keys, jnp.asarray(active_np))
        return toks, keys

    def free(self, slot: int) -> None:
        pass


class _PagedBackend:
    """Block-pool cache (decode_step_paged): slots address KV through
    per-stream block tables over a shared arena, so occupancy is
    token-budgeted — admission asks for exactly
    ceil(min(plen + budget, max_len) / block_size) blocks, a long
    conversation no longer pins a worst-case lane, and block-aligned
    prompt prefixes can be shared through the content-addressed cache
    (filters/kvpool.py)."""

    def __init__(self, filt: "LlmFilter", m: int, max_len: int):
        import jax.numpy as jnp

        self.f = filt
        self.max_len = max_len
        self.bs = filt._block_size
        self.w = -(-max_len // self.bs)
        self.mgr = filt._pool_mgr
        self.pool = filt._tfm.init_kv_pool(filt._cfg, self.mgr.n_blocks,
                                           self.bs)
        self.table_np = np.zeros((m, self.w), np.int32)
        self._table_dev = None
        self.index = jnp.zeros((m,), jnp.int32)
        self.logits = jnp.zeros((m, filt._cfg.vocab), jnp.float32)
        self.blocks: List[List[int]] = [[] for _ in range(m)]

    def _table(self):
        import jax.numpy as jnp

        if self._table_dev is None:
            self._table_dev = jnp.asarray(self.table_np)
        return self._table_dev

    def _need(self, plen: int, budget: int) -> int:
        span = max(plen, min(plen + int(budget), self.max_len))
        return -(-span // self.bs)

    def _insert_span(self, blocks: List[int], k_np, v_np,
                     valid: int) -> None:
        """Block-align (k_np, v_np) [L, n, H, Dh] (first ``valid`` rows
        real) and write them into ``blocks``. Rows past ``valid`` are
        zeros the decode loop overwrites before its validity mask can
        reach them — the same padded-tail argument as prefill's."""
        import jax.numpy as jnp

        layers, _, heads, hd = k_np.shape
        spanf = len(blocks) * self.bs
        kb = np.zeros((layers, spanf, heads, hd), k_np.dtype)
        vb = np.zeros((layers, spanf, heads, hd), v_np.dtype)
        n = min(int(valid), spanf, k_np.shape[1])
        kb[:, :n] = k_np[:, :n]
        vb[:, :n] = v_np[:, :n]
        sh = (layers, len(blocks), self.bs, heads, hd)
        self.pool = self.f._pool_insert(
            self.pool, jnp.asarray(kb.reshape(sh)),
            jnp.asarray(vb.reshape(sh)),
            jnp.asarray(np.asarray(blocks, np.int32)))

    def _suffix_prefill(self, past_k, past_v, past_len: int,
                        suffix: np.ndarray):
        """One prefill-with-past dispatch over pow2-bucketed shapes
        (O(log^2) compiled variants across all split points)."""
        import jax.numpy as jnp

        sb = 8
        while sb < suffix.size:
            sb *= 2
        padded = np.zeros(sb, np.int32)
        padded[:suffix.size] = suffix
        return self.f._prefill_past(
            self.f._params, past_k, past_v,
            jnp.asarray(past_len, jnp.int32), jnp.asarray(padded[None]),
            jnp.asarray(suffix.size, jnp.int32))

    def admit(self, slot: int, prompt: np.ndarray, budget: int) -> None:
        from .kvpool import chain_hashes

        import jax.numpy as jnp

        f = self.f
        plen = int(prompt.size)
        need = self._need(plen, budget)
        hashes = chain_hashes(prompt, self.bs)     # full blocks only
        # adoption never covers the whole prompt: at least one suffix
        # token recomputes (logits must come from somewhere), and the
        # first decode-written block stays stream-private, which is
        # what makes shared blocks read-only by construction
        cover_cap = (plen - 1) // self.bs
        cov = self.mgr.lookup(hashes[:cover_cap]) if f._prefix_cache \
            else []
        fresh = self.mgr.alloc(need - len(cov))
        if fresh is None:
            if cov:
                self.mgr.release(cov)
            raise _PoolFull(f"need {need - len(cov)} blocks")
        allb = list(cov) + list(fresh)
        p0 = len(cov) * self.bs
        try:
            if cov:
                nbb = 1
                while nbb < len(cov):
                    nbb *= 2
                phys_pad = list(cov) + [cov[-1]] * (nbb - len(cov))
                pk, pv = f._pool_gather(
                    self.pool, jnp.asarray(np.asarray(phys_pad, np.int32)))
                l1, sk, sv = self._suffix_prefill(pk, pv, p0, prompt[p0:])
                f.stats.add(prefill_dispatches=1, prefill_cached_tokens=p0,
                            prefill_computed_tokens=plen - p0)
                self._insert_span(fresh, np.asarray(sk), np.asarray(sv),
                                  plen - p0)
            else:
                l1, c1 = f._prefill_prompt(prompt, self.max_len)
                self._insert_span(allb, np.asarray(c1["k"][:, 0]),
                                  np.asarray(c1["v"][:, 0]), plen)
            if f._prefix_cache and hashes:
                self.mgr.commit(hashes, allb[:len(hashes)])
            self._seat(slot, allb, need, plen, l1)
        except BaseException:
            # admission failed after taking refs: hand every block back.
            # (If commit already ran, release only drops the stream
            # refs — the cache's own refs legitimately keep the prefix
            # blocks resident.)
            self.mgr.release(allb)
            raise

    def admit_handoff(self, slot: int, flat: np.ndarray, kv: Dict,
                      budget: int) -> None:
        """Fold a wire-shipped KV prefix (edge/kv.py handoff dict) into
        the pool. ``flat`` may extend the shipped prompt with tokens a
        pre-crash replica already emitted (snapshot re-adoption): that
        suffix is regrown by one prefill-with-past over the shipped
        prefix, so resurrection costs the suffix, not the prompt."""
        import jax.numpy as jnp

        f = self.f
        plen = int(flat.size)
        t_ship = int(np.asarray(kv["prompt"]).size)
        k_np = np.asarray(kv["k"])
        v_np = np.asarray(kv["v"])
        if k_np.ndim != 4 or k_np.shape[1] < t_ship:
            raise ValueError(f"llm: malformed KV handoff {k_np.shape}")
        f.stats.add(kv_shipped_tokens=t_ship)
        if plen > t_ship:
            pb = 8
            while pb < t_ship:
                pb *= 2
            layers, _, heads, hd = k_np.shape
            pk = np.zeros((layers, pb, heads, hd), k_np.dtype)
            pv = np.zeros((layers, pb, heads, hd), v_np.dtype)
            pk[:, :t_ship] = k_np[:, :t_ship]
            pv[:, :t_ship] = v_np[:, :t_ship]
            l1, sk, sv = self._suffix_prefill(
                jnp.asarray(pk), jnp.asarray(pv), t_ship, flat[t_ship:])
            f.stats.add(prefill_dispatches=1,
                        prefill_computed_tokens=plen - t_ship)
            full_k = np.concatenate(
                [k_np[:, :t_ship],
                 np.asarray(sk)[:, :plen - t_ship].astype(k_np.dtype)],
                axis=1)
            full_v = np.concatenate(
                [v_np[:, :t_ship],
                 np.asarray(sv)[:, :plen - t_ship].astype(v_np.dtype)],
                axis=1)
        else:
            import jax.numpy as _jnp
            l1 = _jnp.asarray(np.asarray(kv["logits"],
                                         np.float32).reshape(1, -1))
            full_k, full_v = k_np, v_np
        need = self._need(plen, budget)
        fresh = self.mgr.alloc(need)
        if fresh is None:
            raise _PoolFull(f"need {need} blocks")
        try:
            self._insert_span(fresh, full_k, full_v, plen)
            if f._prefix_cache:
                from .kvpool import chain_hashes
                hashes = chain_hashes(np.asarray(kv["prompt"], np.int32),
                                      self.bs)
                usable = min(len(hashes), need)
                if usable:
                    self.mgr.commit(hashes[:usable], fresh[:usable])
            self._seat(slot, list(fresh), need, plen, l1)
        except BaseException:
            # a failed handoff fold must not strand the receiver's
            # blocks: the sender only counts kv_handoff_errors, so a
            # leaked ref here would shrink the pool forever
            self.mgr.release(list(fresh))
            raise

    def _seat(self, slot: int, allb: List[int], need: int, plen: int,
              l1) -> None:
        self.table_np[slot, :need] = allb
        self.table_np[slot, need:] = 0
        self._table_dev = None
        self.index = self.index.at[slot].set(plen)
        self.logits = self.logits.at[slot].set(l1[0])
        self.blocks[slot] = allb

    def step(self, tok, active_np) -> None:
        import jax.numpy as jnp

        self.logits, self.pool, self.index = self.f._decode_paged(
            self.f._params, self.pool, self._table(), self.index, tok,
            jnp.asarray(active_np))

    def chunk(self, k: int, temperature: float, keys, active_np):
        import jax.numpy as jnp

        toks, self.logits, self.pool, self.index, keys = \
            self.f._chunk_fn_paged(k, temperature)(
                self.f._params, self.pool, self._table(), self.index,
                self.logits, keys, jnp.asarray(active_np))
        return toks, keys

    def free(self, slot: int) -> None:
        if self.blocks[slot]:
            self.mgr.release(self.blocks[slot])
            self.blocks[slot] = []


@register_filter
class LlmFilter(FilterFramework):
    NAME = "llm"
    EXTENSIONS = (".gguf",)  # reference auto-detect parity (llamacpp slot)

    def __init__(self):
        self._params = None
        self._cfg = None
        self._decode = None
        self._opts: Dict[str, str] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # continuous-batching scheduler state (n_parallel > 1)
        self._pending: List[tuple] = []
        self._cond = threading.Condition()
        self._sched: Optional[threading.Thread] = None
        # checkpoint/: the live slot table (published by _sched_body,
        # stream bookkeeping mutated under _cond) and the stream state
        # recovered from a preemption snapshot, adopted on prompt match
        # at the next invoke_async (see snapshot_state/restore_state)
        self._streams: Optional[List[Optional[Dict[str, Any]]]] = None
        self._recovered: Optional[Dict[str, Any]] = None
        # disaggregated serving (role prop / paged pool)
        self._role = "both"
        self._paged = False
        self._backend = None
        self._pool_mgr = None
        self._kv_rx = None
        self._kv_tx = None

    def open(self, props: FilterProperties) -> None:
        import jax

        from ..models import transformer as tfm

        model = props.model_files[0] if props.model_files else ""
        if model.startswith("zoo://"):
            parsed = urllib.parse.urlparse(model)
            kwargs = {k: v[0] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
            name = parsed.netloc or parsed.path.lstrip("/")
            if name != "gpt":
                raise ValueError(f"llm filter expects zoo://gpt, got {name}")
            self._cfg = tfm.GPTConfig(
                vocab=int(kwargs.get("vocab", "32000")),
                d_model=int(kwargs.get("d_model", "512")),
                n_heads=int(kwargs.get("n_heads", "8")),
                n_layers=int(kwargs.get("n_layers", "6")))
            self._params = tfm.init_params(
                self._cfg, jax.random.PRNGKey(int(kwargs.get("seed", "0"))))
            if "params_dir" in kwargs:
                # trained weights from an orbax checkpoint (e.g. saved by
                # tensor_trainer / trainers/checkpoint.py) — the random
                # init above provides the restore template
                from ..trainers.checkpoint import restore_params
                self._params = restore_params(kwargs["params_dir"],
                                              self._params)
        elif model.endswith(".py"):
            ns: Dict[str, Any] = {}
            with open(model) as f:
                exec(compile(f.read(), model, "exec"), ns)  # noqa: S102 — user script
            self._params, self._cfg = ns["get_lm"]()
        elif model.endswith(".gguf"):
            # the extension routes here for reference auto-detect parity,
            # but gguf weight unpacking is out of scope — fail with a
            # pointer instead of a generic loader error
            raise NotImplementedError(
                "llm: .gguf weight loading is not implemented; export "
                "the weights to a get_lm() python module instead (see "
                "Documentation/tutorials/generative-pipelines.md)")
        else:
            raise ValueError(f"llm filter cannot load model {model!r}")
        self._opts = _parse_custom(props.custom_properties)
        cfg = self._cfg

        def step(params, cache, token):
            return tfm.decode_step(params, cache, token, cfg)

        def pre(params, cache, tokens, true_len):
            return tfm.prefill(params, cache, tokens, cfg,
                               true_len=true_len)

        self._decode = jax.jit(step)
        self._prefill = jax.jit(pre)
        self._decode_multi = jax.jit(
            lambda p, c, t, a: tfm.decode_step_multi(p, c, t, a, cfg))
        self._insert = jax.jit(tfm.cache_insert)
        self._tfm = tfm
        self._n_parallel = int(self._opts.get("n_parallel", "1"))
        # custom=chunk:K folds K sample+decode rounds into one scanned
        # dispatch (models/transformer.py decode_chunk_multi): dispatches
        # AND host round trips per token drop K-fold. Token streams are
        # bit-identical to chunk:1; the tradeoff is admission latency in
        # n_parallel mode (a new prompt waits for the current chunk).
        self._chunk = max(1, int(self._opts.get("chunk", "1")))
        self._chunk_jits: Dict[tuple, Any] = {}
        self._sampling_cache = None  # re-parse on every open()
        # -- disaggregated serving / paged pool --------------------------
        self._role = self._opts.get("role", "both")
        if self._role not in ("both", "prefill", "decode"):
            raise ValueError(f"llm: unknown role {self._role!r}; "
                             "expected prefill|decode|both")
        self._paged = (self._opts.get("paged", "false").lower() in _TRUE
                       or self._role == "decode")
        self._prefix_cache = self._opts.get(
            "prefix_cache", "true").lower() in _TRUE
        self._kv_precision = self._opts.get("kv_precision", "none")
        self._block_size = max(1, int(self._opts.get("block_size", "16")))
        self._batch_max_len = int(self._opts.get(
            "max_len", str(DEFAULT_BATCH_MAX_LEN)))
        self._backend = None
        self._pool_mgr = None
        if self._paged:
            if self._n_parallel < 2:
                raise ValueError(
                    "llm: paged/decode mode requires n_parallel>1 — the "
                    "block pool backs the continuous-batching scheduler")
            from .kvpool import KVBlockPool
            w = -(-self._batch_max_len // self._block_size)
            # default budget matches the contiguous layout's worst case,
            # so paged-by-default admits at least what lanes would
            n_blocks = int(self._opts.get("pool_blocks",
                                          str(self._n_parallel * w)))
            self._pool_mgr = KVBlockPool(n_blocks, self._block_size,
                                         name="llm")
            max_len = self._batch_max_len
            self._decode_paged = jax.jit(
                lambda p, pool, tbl, idx, t, a: tfm.decode_step_paged(
                    p, pool, tbl, idx, t, a, cfg, max_len=max_len))
            self._pool_insert = jax.jit(tfm.pool_insert)
            self._pool_gather = jax.jit(tfm.pool_gather)
            self._prefill_past = jax.jit(
                lambda p, pk, pv, pl, toks, tl: tfm.prefill_with_past(
                    p, pk, pv, pl, toks, cfg, true_len=tl))
        with self._cond:
            # prompts queued before a close() belong to the previous
            # session (and carry its ctx buffers) — never replay them
            self._pending.clear()
        self._stop.clear()
        # dispatch accounting: prompts of any length must cost ONE
        # prefill dispatch (≙ llamacpp n_batch), then one per token STEP
        # (shared across n_parallel streams). decode_steps counts the
        # ACTUAL weight-reading steps executed (a chunked dispatch runs
        # an adaptive k <= chunk of them) — the honest multiplier for
        # decode bandwidth accounting. The token-granular prefill
        # counters split prompt work into locally computed vs
        # prefix-cache-warm vs wire-shipped tokens: computed is the
        # chip-time cost, the other two are the savings.
        self.stats = Counters(prefill_dispatches=0, decode_dispatches=0,
                              decode_steps=0, prefill_computed_tokens=0,
                              prefill_cached_tokens=0, kv_shipped_tokens=0,
                              kv_handoffs_in=0, kv_handoffs_out=0,
                              kv_handoff_errors=0)
        if self._role == "decode" or "handoff_port" in self._opts:
            from ..edge.kv import KvReceiver
            self._kv_rx = KvReceiver(
                "0.0.0.0", int(self._opts.get("handoff_port", "0")),
                self._on_kv_handoff, precision=self._kv_precision,
                name="llm-kv-rx", stats=self.stats).start()

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._kv_rx is not None:
            self._kv_rx.stop()
            self._kv_rx = None
        if self._kv_tx is not None:
            self._kv_tx.close()
            self._kv_tx = None
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        if self._sched is not None:
            self._sched.join(timeout=5.0)
            self._sched = None
        self._params = None
        self._decode = None

    @property
    def handoff_port(self) -> Optional[int]:
        """The bound KV_XFER port of a decode-role filter (resolves
        handoff_port:0 to the ephemeral port the OS picked)."""
        return self._kv_rx.bound_port if self._kv_rx is not None else None

    def get_model_info(self):
        # prompt length is per-buffer (dynamic): input derives from caps
        return None, TensorsInfo.make("int32", "1")

    def set_input_info(self, info: TensorsInfo) -> Optional[TensorsInfo]:
        return TensorsInfo.make("int32", "1")

    # -- generation -------------------------------------------------------
    def _check_prompt(self, prompt: np.ndarray, max_len: int) -> None:
        """Fail before dispatch: the jitted cache write would raise an
        opaque XLA shape error (≙ llamacpp context-overflow error)."""
        if prompt.size == 0:
            raise ValueError("llm: empty prompt")
        if prompt.size > max_len:
            raise ValueError(
                f"llm: prompt length {prompt.size} exceeds max_len "
                f"{max_len}; raise custom=max_len:N")

    def _prefill_prompt(self, prompt: np.ndarray, max_len: int):
        """Bucket-pad the prompt and run ONE prefill dispatch into a
        fresh batch-1 cache of ``max_len``; returns (logits, cache).
        Prompts pad to power-of-two buckets so streams of varied lengths
        compile O(log max_len) prefill shapes, not one per length."""
        import jax.numpy as jnp

        bucket = 8
        while bucket < prompt.size:
            bucket *= 2
        bucket = min(bucket, max_len)
        padded = np.zeros(bucket, np.int32)
        padded[:prompt.size] = prompt
        cache = self._tfm.init_cache(self._cfg, batch=1, max_len=max_len)
        logits, cache = self._prefill(
            self._params, cache, jnp.asarray(padded[None, :]),
            jnp.asarray(prompt.size, jnp.int32))
        self.stats.add(prefill_dispatches=1,
                       prefill_computed_tokens=int(prompt.size))
        return logits, cache

    def _sampling(self):
        """(top_k, top_p) from custom properties (llamacpp sampler-chain
        parity: same knobs, same order — nucleus before temperature).
        Parsed once: this sits on the per-token host loop."""
        cached = getattr(self, "_sampling_cache", None)
        if cached is None:
            cached = self._sampling_cache = (
                int(self._opts.get("top_k", "0")),
                float(self._opts.get("top_p", "1.0")))
        return cached

    def _sample_host(self, sub, logits, temperature):
        """One host-loop sampling step, via the SAME in-graph helper the
        scanned chunk body uses, so every path draws identical tokens."""
        return self._tfm.sample_logits(sub[None], logits, temperature,
                                       *self._sampling())[:1]

    def _chunk_fn(self, steps: int, temperature: float):
        """Jitted K-step decode chunk, cached per (steps, sampling)."""
        top_k, top_p = self._sampling()
        key = (steps, float(temperature), top_k, top_p)
        fn = self._chunk_jits.get(key)
        if fn is None:
            import jax
            tfm, cfg = self._tfm, self._cfg
            fn = jax.jit(lambda p, c, l, k, a: tfm.decode_chunk_multi(
                p, c, l, k, a, cfg, steps=steps, temperature=temperature,
                top_k=top_k, top_p=top_p))
            self._chunk_jits[key] = fn
        return fn

    def _chunk_fn_paged(self, steps: int, temperature: float):
        """Paged twin of _chunk_fn (decode_chunk_paged over the pool +
        block tables), cached per (steps, sampling)."""
        top_k, top_p = self._sampling()
        key = ("paged", steps, float(temperature), top_k, top_p)
        fn = self._chunk_jits.get(key)
        if fn is None:
            import jax
            tfm, cfg = self._tfm, self._cfg
            max_len = self._batch_max_len
            fn = jax.jit(
                lambda p, pool, tbl, idx, l, k, a: tfm.decode_chunk_paged(
                    p, pool, tbl, idx, l, k, a, cfg, steps=steps,
                    max_len=max_len, temperature=temperature,
                    top_k=top_k, top_p=top_p))
            self._chunk_jits[key] = fn
        return fn

    def _generate(self, prompt: np.ndarray, emit) -> None:
        import jax
        import jax.numpy as jnp

        prompt = np.asarray(prompt).reshape(-1)
        max_tokens = int(self._opts.get("max_tokens", "16"))
        temperature = float(self._opts.get("temperature", "0"))
        # the DEFAULT max_len derives from the bucket (not the raw
        # prompt length) so the cache shape — and with it the
        # decode-step compilation — is bucket-stable too
        bucket = 8
        while bucket < max(prompt.size, 1):
            bucket *= 2
        max_len = int(self._opts.get("max_len", str(bucket + max_tokens)))
        key = jax.random.PRNGKey(int(self._opts.get("seed", "0")))
        self._check_prompt(prompt, max_len)
        logits, cache = self._prefill_prompt(prompt, max_len)
        pos = prompt.size  # host-side cache index: no per-token device sync
        if self._chunk > 1:
            self._generate_chunked(logits, cache, pos, max_tokens, max_len,
                                   temperature, key, emit)
            return
        for i in range(max_tokens):
            if self._stop.is_set():
                return
            if temperature > 0:
                key, sub = jax.random.split(key)
                tok = self._sample_host(sub, logits, temperature)
            else:
                tok = jnp.argmax(logits, -1)
            # per-token emit IS the streaming boundary: materialize via
            # the sanctioned device_get, not an implicit __array__ sync
            emit(jax.device_get(tok).astype(np.int32))
            if i + 1 >= max_tokens or pos >= max_len:
                return  # nothing left to decode: skip the trailing step
            logits, cache = self._decode(self._params, cache,
                                         tok.astype(jnp.int32))
            self.stats.add(decode_dispatches=1, decode_steps=1)
            pos += 1

    def _generate_chunked(self, logits, cache, pos, max_tokens, max_len,
                          temperature, key, emit) -> None:
        """Single-stream chunked decode: [chunk] tokens per dispatch and
        per host fetch. Emits the exact token stream of the per-token
        loop (same key-split order, same capacity cutoff at max_len)."""
        import jax
        import jax.numpy as jnp

        mcache = {"k": cache["k"], "v": cache["v"],
                  "index": jnp.broadcast_to(cache["index"], (1,))}
        keys = key[None]
        active = jnp.ones((1,), bool)
        remaining = max_tokens
        while remaining > 0 and not self._stop.is_set():
            # each scan step samples THEN decodes; decode writes at the
            # stream's cache index, legal while index <= max_len-1
            k = min(self._chunk, remaining, max_len - pos)
            if k <= 0:
                # cache full: the per-token loop still emits one final
                # sampled token before stopping — mirror it, no decode
                if temperature > 0:
                    key2, sub = jax.random.split(keys[0])
                    tok = self._sample_host(sub, logits, temperature)
                else:
                    tok = jnp.argmax(logits, -1)
                emit(jax.device_get(tok).astype(np.int32))
                return
            toks, logits, mcache, keys = self._chunk_fn(k, temperature)(
                self._params, mcache, logits, keys, active)
            self.stats.add(decode_dispatches=1, decode_steps=k)
            toks_host = np.asarray(toks)  # ONE fetch for k tokens
            for j in range(k):
                emit(toks_host[j].astype(np.int32))
            pos += k
            remaining -= k

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        """Sync path: return the whole generation as one int32 tensor."""
        tokens: List[np.ndarray] = []
        self._generate(np.asarray(inputs[0]), tokens.append)
        return [np.concatenate(tokens) if tokens
                else np.zeros((0,), np.int32)]

    def invoke_async(self, inputs: Sequence[Any], ctx: Any = None) -> None:
        """1-in/N-out: one output frame per generated token, each
        dispatched with this invoke's ``ctx``. A prefill-role filter
        dispatches nothing: it ships the prompt's KV to its decode home
        and the decode replica emits the tokens."""
        prompt = np.asarray(inputs[0])
        if self._role == "prefill":
            flat = prompt.reshape(-1).astype(np.int32)
            self._check_prompt(flat, self._batch_max_len)
            t = threading.Thread(target=self._prefill_and_ship,
                                 args=(flat, ctx),
                                 name="llm-prefill-ship", daemon=True)
            self._threads.append(t)
            t.start()
            return
        if self._n_parallel > 1:
            # validate on the CALLER's thread so an oversized prompt is a
            # visible invoke error, not a silent scheduler drop
            flat = prompt.reshape(-1)
            self._check_prompt(flat, self._batch_max_len)
            with self._cond:
                rem = None
                if self._recovered is not None:
                    # resurrection: a re-submitted prompt that matches a
                    # snapshotted stream continues where it stopped —
                    # the emitted tokens (already delivered through the
                    # acked session pre-crash) join the prefill context
                    # and only the undelivered remainder is generated
                    rem, flat = self._adopt_recovered_locked(flat)
                self._enqueue_stream_locked((flat, ctx, rem))
            return

        def run():
            try:
                self._generate(
                    prompt, lambda tok: self._dispatch([tok], ctx))
            except Exception:  # noqa: BLE001
                logger.exception("llm generation failed")

        t = threading.Thread(target=run, name="llm-generate", daemon=True)
        self._threads.append(t)
        t.start()

    def _enqueue_stream_locked(self, entry: tuple) -> None:
        """Queue a stream for the scheduler (caller holds _cond).
        Start-check under the lock: two racing submitters must not
        spawn two schedulers splitting one slot pool."""
        self._pending.append(entry)
        self._cond.notify_all()
        if self._sched is None or not self._sched.is_alive():
            self._sched = threading.Thread(
                target=self._sched_loop, name="llm-sched", daemon=True)
            self._sched.start()

    # -- prefill/decode split (role prop + KV handoff) ---------------------
    def _handoff_sender(self):
        with self._cond:
            if self._kv_tx is None:
                target = self._opts.get("handoff", "")
                if not target:
                    raise ValueError(
                        "llm: role:prefill requires custom=handoff:host:port")
                host, _, port = target.rpartition(":")
                from ..edge.kv import KvSender
                self._kv_tx = KvSender(host or "127.0.0.1", int(port),
                                       precision=self._kv_precision,
                                       stats=self.stats)
            return self._kv_tx

    def _prefill_and_ship(self, flat: np.ndarray, ctx: Any) -> None:
        """Prefill-role path: ONE prompt pass, then ship the KV prefix
        + last logits to the decode home over KV_XFER. The trace
        context (minted here when the invoke carried none) rides the
        wire, so prefill -> handoff -> decode renders as one tree."""
        from ..checkpoint.state import token_sha
        from ..obs import spans as _spans
        try:
            max_tokens = int(self._opts.get("max_tokens", "16"))
            t0 = time.time_ns()
            tctx = _ctx_of(ctx)
            if tctx is None and _spans.ENABLED:
                from ..obs import context as _obs_ctx
                tctx = _obs_ctx.TraceContext(_obs_ctx.next_id(), 0, t0)
            l1, c1 = self._prefill_prompt(flat, self._batch_max_len)
            t = int(flat.size)
            k_np = np.asarray(c1["k"][:, 0, :t])
            v_np = np.asarray(c1["v"][:, 0, :t])
            if tctx is not None:
                _spans.record_span("llm-prefill", "llm", t0,
                                   max(0, time.time_ns() - t0), tctx)
            ack = self._handoff_sender().send(
                token_sha(flat), flat, k_np, v_np,
                np.asarray(l1[0], np.float32), remaining=max_tokens,
                seed=int(self._opts.get("seed", "0")), ctx=tctx)
            self.stats.inc("kv_handoffs_out")
            if not ack.get("adopted"):
                self.stats.inc("kv_handoff_errors")
                logger.error("llm: decode replica refused stream %s",
                             ack.get("sid"))
        except Exception:  # noqa: BLE001 — ship failures must be visible, not fatal
            self.stats.inc("kv_handoff_errors")
            logger.exception("llm: kv handoff failed")

    def _on_kv_handoff(self, d: Dict) -> bool:
        """KvReceiver callback (per-connection listener thread): queue a
        shipped stream for paged admission. The returned flag becomes
        the KV_ACK ``adopted`` receipt — False tells the prefill side
        to try another decode home."""
        if self._stop.is_set() or self._params is None:
            return False
        flat = np.asarray(d["prompt"], np.int32).reshape(-1)
        try:
            self._check_prompt(flat, self._batch_max_len)
        except ValueError:
            logger.exception("llm: rejected KV handoff %s", d.get("sid"))
            return False
        with self._cond:
            rem = int(d.get("remaining", 0)) or None
            if self._recovered is not None:
                # a re-shipped conversation adopts its snapshot: the
                # pre-crash emitted tokens join the context and only
                # the undelivered remainder is generated
                rem2, flat = self._adopt_recovered_locked(flat)
                if rem2 is not None:
                    rem = rem2
            self._enqueue_stream_locked((flat, d.get("sid"), rem, d))
        self.stats.inc("kv_handoffs_in")
        return True

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    def snapshot_state(self, snap_dir) -> Optional[Dict[str, Any]]:
        """Continuous-batching state for a preemption snapshot: per
        stream (queued or mid-generation) the prompt, the tokens already
        emitted, and the remaining budget. The KV cache itself is NOT
        saved — it is recomputed by one prefill over prompt+emitted at
        adoption time (cheaper and version-proof next to dumping a
        device cache). Single-stream mode (n_parallel=1) keeps no
        scheduler state and snapshots nothing."""
        with self._cond:
            pend = [{"prompt": np.asarray(e[0], np.int32).tolist(),
                     "emitted": [], "remaining": e[2]}
                    for e in self._pending]
            act = [{"prompt": s["prompt"].tolist(),
                    "emitted": list(s["emitted"]),
                    "remaining": int(s["remaining"])}
                   for s in (self._streams or [])
                   if s is not None and s["remaining"] > 0]
        if not pend and not act:
            return None
        return {"streams": act + pend}

    def restore_state(self, state, snap_dir) -> None:
        """Stash recovered streams; they are adopted lazily when a
        re-submitted prompt (the client's RESUME-driven resend, or a
        re-shipped KV handoff) matches one of them — see invoke_async
        and _on_kv_handoff."""
        with self._cond:
            self._recovered = state

    def _adopt_recovered_locked(self, flat: np.ndarray):
        """Match an incoming prompt against the recovered streams
        (caller holds _cond). Matching is by content digest
        (checkpoint.state.token_sha — the same digest that names wire
        handoffs), computed once per entry and once for the incoming
        prompt, instead of a full array comparison per entry. On a
        hit: continuation — the pre-crash prompt + already-emitted
        tokens become the prefill context and only the remaining
        budget is generated. Returns (remaining_override,
        prompt_to_queue)."""
        from ..checkpoint.state import token_sha

        entries = self._recovered.get("streams") or []
        sha = token_sha(flat)
        for i, ent in enumerate(entries):
            esha = ent.get("_sha")
            if esha is None:
                esha = ent["_sha"] = token_sha(
                    np.asarray(ent.get("prompt") or [], np.int32))
            if esha == sha:
                entries.pop(i)
                if not entries:
                    self._recovered = None
                emitted = np.asarray(ent.get("emitted") or [], np.int32)
                rem = ent.get("remaining")
                if emitted.size:
                    flat = np.concatenate(
                        [flat.astype(np.int32), emitted])
                return rem, flat
        return None, flat

    # -- continuous-batching scheduler (n_parallel > 1) --------------------
    def _sched_loop(self) -> None:
        """Decode M streams per dispatch. Admission: pending prompts are
        prefilled (one bucketed dispatch each) into free cache slots;
        every active slot then advances one token per SHARED decode
        dispatch, and finished slots free up mid-flight for waiting
        prompts — continuous batching, not static batching."""
        try:
            self._sched_body()
        except Exception:  # noqa: BLE001 — daemon thread: log, don't die silent
            logger.exception("llm scheduler failed; in-flight streams lost")

    def _finish_span(self, s: Dict[str, Any]) -> None:
        """A stream just finished: close its llm-decode span so the
        conversation's trace tree has a terminal node on this replica."""
        tctx = s.get("tctx")
        if tctx is None:
            return
        from ..obs import spans as _spans
        t0 = s.get("t0") or time.time_ns()
        _spans.record_span("llm-decode", "llm", t0,
                           max(0, time.time_ns() - t0), tctx)

    def _sched_body(self) -> None:
        import jax
        import jax.numpy as jnp

        m = self._n_parallel
        max_tokens = int(self._opts.get("max_tokens", "16"))
        max_len = self._batch_max_len
        temperature = float(self._opts.get("temperature", "0"))
        seed = int(self._opts.get("seed", "0"))
        # the cache layout is a pluggable backend: contiguous per-slot
        # lanes (stream-counted) or the paged block pool
        # (token-budgeted). Admission, sampling, dispatch bookkeeping
        # and snapshots are THIS one loop either way — the parity gate
        # only has to reason about the cache math, not two schedulers.
        backend = (_PagedBackend(self, m, max_len) if self._paged
                   else _ContigBackend(self, m, max_len))
        self._backend = backend
        tok = jnp.zeros((m,), jnp.int32)
        streams: List[Optional[Dict[str, Any]]] = [None] * m
        with self._cond:
            self._streams = streams  # published for snapshot_state
        while not self._stop.is_set():
            # -- admit pending streams into free slots
            with self._cond:
                while all(s is None for s in streams) and not self._pending \
                        and not self._stop.is_set():
                    self._cond.wait(0.1)
                if self._stop.is_set():
                    return
                admit = []
                for slot in range(m):
                    if streams[slot] is None and self._pending:
                        admit.append((slot, self._pending.pop(0)))
            requeue = []
            for slot, entry in admit:
                prompt, ctx, rem = entry[0], entry[1], entry[2]
                kv = entry[3] if len(entry) > 3 else None
                budget = max_tokens if rem is None else int(rem)
                t_admit = time.time_ns()
                try:
                    if kv is not None:
                        backend.admit_handoff(slot, prompt, kv, budget)
                    else:
                        self._check_prompt(prompt, max_len)
                        backend.admit(slot, prompt, budget)
                except _PoolFull:
                    # token-budgeted admission: not enough KV blocks
                    # right now — requeue; running streams release
                    # blocks as they finish
                    requeue.append(entry)
                    continue
                except Exception:  # noqa: BLE001 — drop THIS prompt only
                    logger.exception("llm: prompt rejected at admission")
                    continue
                tctx = kv.get("ctx") if kv is not None else _ctx_of(ctx)
                if tctx is not None and kv is None:
                    from ..obs import spans as _spans
                    _spans.record_span("llm-prefill", "llm", t_admit,
                                       max(0, time.time_ns() - t_admit),
                                       tctx)
                # per-stream PRNG key: the sample sequence matches the
                # n_parallel=1 path for the same seed, independent of
                # which other prompts happen to be in flight. rem
                # overrides the budget for a stream adopted from a
                # preemption snapshot (the rest was emitted pre-crash);
                # handoff streams sample with the seed the prefill
                # replica shipped, so the split emits the monolithic
                # token stream.
                streams[slot] = {"ctx": ctx,
                                 "remaining": budget,
                                 "pos": int(prompt.size),
                                 "prompt": np.asarray(prompt,
                                                      np.int32).copy(),
                                 "emitted": [],
                                 "key": jax.random.PRNGKey(
                                     int(kv["seed"]) if kv is not None
                                     else seed),
                                 "tctx": tctx, "t0": t_admit}
            if requeue:
                with self._cond:
                    if all(s is None for s in streams):
                        # nothing is running, so nothing will ever free
                        # blocks: the head request exceeds the whole
                        # pool — drop it loudly instead of deadlocking
                        head = requeue.pop(0)
                        logger.error(
                            "llm: stream of %d tokens needs more KV "
                            "blocks than pool_blocks=%d holds; dropped",
                            int(np.asarray(head[0]).size),
                            self._pool_mgr.n_blocks)
                    self._pending[:0] = requeue
            active_np = np.array([s is not None for s in streams])
            if not active_np.any():
                continue
            if self._chunk > 1:
                self._sched_chunk(streams, active_np, backend, max_len,
                                  temperature)
                continue
            # -- sample on device, D2H just the M token ids
            if temperature > 0:
                subs = []
                for s in streams:
                    if s is None:
                        subs.append(jax.random.PRNGKey(0))
                        continue
                    s["key"], sub = jax.random.split(s["key"])
                    subs.append(sub)
                tok = self._tfm.sample_logits(
                    jnp.stack(subs), backend.logits, temperature,
                    *self._sampling())
            else:
                tok = jnp.argmax(backend.logits, -1)
            tok = tok.astype(jnp.int32)
            tok_host = jax.device_get(tok)  # ONE fetch for all slots
            for slot, s in enumerate(streams):
                if s is None:
                    continue
                self._dispatch([tok_host[slot:slot + 1]], s["ctx"])
                with self._cond:
                    # bookkeeping under _cond: a preemption snapshot
                    # reads (prompt, emitted, remaining) coherently
                    s["emitted"].append(int(tok_host[slot]))
                    s["remaining"] -= 1
                    s["pos"] += 1
                # pos is one past the next decode's cache-write position
                # (the write lands at pos-1), so the stream survives
                # while pos <= max_len — matching the single-stream
                # loop's emit-then-check ordering exactly
                if s["remaining"] <= 0 or s["pos"] > max_len:
                    streams[slot] = None
                    # keep the mask current: a lane that just finished
                    # must not keep writing/advancing its cache in the
                    # trailing decode (the decode step also position-
                    # guards at max_len)
                    active_np[slot] = False
                    backend.free(slot)
                    self._finish_span(s)
            if active_np.any():
                backend.step(tok, active_np)
                self.stats.add(decode_dispatches=1, decode_steps=1)

    def _sched_chunk(self, streams, active_np, backend, max_len,
                     temperature) -> None:
        """One chunked round of the continuous-batching loop: K
        sample+decode steps in ONE dispatch, K tokens per stream per
        host fetch. K adapts to the deepest stream still running, so a
        stream never emits past its budget; streams that finish
        mid-chunk have their surplus lane tokens discarded (their lanes
        compute garbage either way). New prompts admit between chunks —
        the admission-latency/throughput knob is ``custom=chunk:K``."""
        import jax
        import jax.numpy as jnp

        # emits each stream still owes; K serves the deepest one fully.
        # The +1 is the capacity tail: the final token a lane emits at
        # pos == max_len is sampled in-scan from the last legal decode's
        # logits — the decode that FOLLOWS that sample is position-
        # guarded inside the decode step (pos < max_len), so it cannot
        # clamp a write onto row max_len-1 (the single-stream invariant
        # of _generate_chunked, enforced in-graph here).
        emits_left = [min(s["remaining"], max_len - s["pos"] + 1)
                      if s else 0 for s in streams]
        k = min(self._chunk, max(emits_left))
        if temperature > 0:
            # one cached filler key for idle slots: a fresh eager
            # PRNGKey per slot per round would cost an RPC each on a
            # remote-attached chip, eroding the chunking win
            if not hasattr(self, "_idle_key"):
                self._idle_key = jax.random.PRNGKey(0)
            keys = jnp.stack([s["key"] if s else self._idle_key
                              for s in streams])
        else:
            keys = jnp.zeros((len(streams), 2), jnp.uint32)
        toks, keys = backend.chunk(k, temperature, keys, active_np)
        self.stats.add(decode_dispatches=1, decode_steps=k)
        toks_host = np.asarray(toks)  # [k, M]: ONE fetch for the chunk
        for slot, s in enumerate(streams):
            if s is None:
                continue
            for j in range(min(k, emits_left[slot])):
                self._dispatch([toks_host[j, slot:slot + 1]], s["ctx"])
                with self._cond:
                    s["emitted"].append(int(toks_host[j, slot]))
                    s["remaining"] -= 1
                    s["pos"] += 1
            if temperature > 0:
                s["key"] = keys[slot]
            if s["remaining"] <= 0 or s["pos"] > max_len:
                streams[slot] = None
                backend.free(slot)
                self._finish_span(s)


register_alias("llamacpp", "llm")
register_alias("llama2c", "llm")
