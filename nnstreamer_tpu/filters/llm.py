"""LLM generative filter — async token streaming on the JAX decode loop.

≙ ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc: 1 prompt in,
N token frames out via the async dispatcher
(nnstreamer_filter_dispatch_output_async, tensor_filter.c:1099-1170).
Here generation is the KV-cache decode loop of models/transformer.py —
static shapes, one jitted decode step reused every token.

model accepts ``zoo://gpt?...`` (zoo spec) or a ``get_lm()`` python file
returning (params, cfg). custom properties (``custom=key:value,...``):
max_tokens, temperature (0 = greedy), top_k, top_p, seed, max_len,
n_parallel, chunk.

``n_parallel:M`` (M>1) turns on continuous-batching decode: up to M
concurrent prompts share ONE decode dispatch per token step (the
TPU-first answer to llamacpp's n_batch, tensor_filter_llamacpp.cc:267)
— prompts are prefetched into per-slot cache lanes as slots free up, so
decode dispatch count scales with max(stream depth), not
streams x tokens.
"""
from __future__ import annotations

import threading
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..tensors.info import TensorsInfo
from ..utils.atomic import Counters
from ..utils.log import logger
from .base import (FilterFramework, FilterProperties,
                   parse_custom_properties as _parse_custom)
from .registry import register_alias, register_filter

# default shared-cache length in n_parallel mode. The batched cache is
# allocated ONCE (static shapes), so unlike the single-stream path the
# default cannot derive from each prompt's bucket; longer prompts need an
# explicit custom=max_len:N.
DEFAULT_BATCH_MAX_LEN = 128


@register_filter
class LlmFilter(FilterFramework):
    NAME = "llm"
    EXTENSIONS = (".gguf",)  # reference auto-detect parity (llamacpp slot)

    def __init__(self):
        self._params = None
        self._cfg = None
        self._decode = None
        self._opts: Dict[str, str] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # continuous-batching scheduler state (n_parallel > 1)
        self._pending: List[tuple] = []
        self._cond = threading.Condition()
        self._sched: Optional[threading.Thread] = None
        # checkpoint/: the live slot table (published by _sched_body,
        # stream bookkeeping mutated under _cond) and the stream state
        # recovered from a preemption snapshot, adopted on prompt match
        # at the next invoke_async (see snapshot_state/restore_state)
        self._streams: Optional[List[Optional[Dict[str, Any]]]] = None
        self._recovered: Optional[Dict[str, Any]] = None

    def open(self, props: FilterProperties) -> None:
        import jax

        from ..models import transformer as tfm

        model = props.model_files[0] if props.model_files else ""
        if model.startswith("zoo://"):
            parsed = urllib.parse.urlparse(model)
            kwargs = {k: v[0] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
            name = parsed.netloc or parsed.path.lstrip("/")
            if name != "gpt":
                raise ValueError(f"llm filter expects zoo://gpt, got {name}")
            self._cfg = tfm.GPTConfig(
                vocab=int(kwargs.get("vocab", "32000")),
                d_model=int(kwargs.get("d_model", "512")),
                n_heads=int(kwargs.get("n_heads", "8")),
                n_layers=int(kwargs.get("n_layers", "6")))
            self._params = tfm.init_params(
                self._cfg, jax.random.PRNGKey(int(kwargs.get("seed", "0"))))
            if "params_dir" in kwargs:
                # trained weights from an orbax checkpoint (e.g. saved by
                # tensor_trainer / trainers/checkpoint.py) — the random
                # init above provides the restore template
                from ..trainers.checkpoint import restore_params
                self._params = restore_params(kwargs["params_dir"],
                                              self._params)
        elif model.endswith(".py"):
            ns: Dict[str, Any] = {}
            with open(model) as f:
                exec(compile(f.read(), model, "exec"), ns)  # noqa: S102 — user script
            self._params, self._cfg = ns["get_lm"]()
        elif model.endswith(".gguf"):
            # the extension routes here for reference auto-detect parity,
            # but gguf weight unpacking is out of scope — fail with a
            # pointer instead of a generic loader error
            raise NotImplementedError(
                "llm: .gguf weight loading is not implemented; export "
                "the weights to a get_lm() python module instead (see "
                "Documentation/tutorials/generative-pipelines.md)")
        else:
            raise ValueError(f"llm filter cannot load model {model!r}")
        self._opts = _parse_custom(props.custom_properties)
        cfg = self._cfg

        def step(params, cache, token):
            return tfm.decode_step(params, cache, token, cfg)

        def pre(params, cache, tokens, true_len):
            return tfm.prefill(params, cache, tokens, cfg,
                               true_len=true_len)

        self._decode = jax.jit(step)
        self._prefill = jax.jit(pre)
        self._decode_multi = jax.jit(
            lambda p, c, t, a: tfm.decode_step_multi(p, c, t, a, cfg))
        self._insert = jax.jit(tfm.cache_insert)
        self._tfm = tfm
        self._n_parallel = int(self._opts.get("n_parallel", "1"))
        # custom=chunk:K folds K sample+decode rounds into one scanned
        # dispatch (models/transformer.py decode_chunk_multi): dispatches
        # AND host round trips per token drop K-fold. Token streams are
        # bit-identical to chunk:1; the tradeoff is admission latency in
        # n_parallel mode (a new prompt waits for the current chunk).
        self._chunk = max(1, int(self._opts.get("chunk", "1")))
        self._chunk_jits: Dict[tuple, Any] = {}
        self._sampling_cache = None  # re-parse on every open()
        with self._cond:
            # prompts queued before a close() belong to the previous
            # session (and carry its ctx buffers) — never replay them
            self._pending.clear()
        self._stop.clear()
        # dispatch accounting: prompts of any length must cost ONE
        # prefill dispatch (≙ llamacpp n_batch), then one per token STEP
        # (shared across n_parallel streams). decode_steps counts the
        # ACTUAL weight-reading steps executed (a chunked dispatch runs
        # an adaptive k <= chunk of them) — the honest multiplier for
        # decode bandwidth accounting.
        self.stats = Counters(prefill_dispatches=0, decode_dispatches=0,
                              decode_steps=0)

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        if self._sched is not None:
            self._sched.join(timeout=5.0)
            self._sched = None
        self._params = None
        self._decode = None

    def get_model_info(self):
        # prompt length is per-buffer (dynamic): input derives from caps
        return None, TensorsInfo.make("int32", "1")

    def set_input_info(self, info: TensorsInfo) -> Optional[TensorsInfo]:
        return TensorsInfo.make("int32", "1")

    # -- generation -------------------------------------------------------
    def _check_prompt(self, prompt: np.ndarray, max_len: int) -> None:
        """Fail before dispatch: the jitted cache write would raise an
        opaque XLA shape error (≙ llamacpp context-overflow error)."""
        if prompt.size == 0:
            raise ValueError("llm: empty prompt")
        if prompt.size > max_len:
            raise ValueError(
                f"llm: prompt length {prompt.size} exceeds max_len "
                f"{max_len}; raise custom=max_len:N")

    def _prefill_prompt(self, prompt: np.ndarray, max_len: int):
        """Bucket-pad the prompt and run ONE prefill dispatch into a
        fresh batch-1 cache of ``max_len``; returns (logits, cache).
        Prompts pad to power-of-two buckets so streams of varied lengths
        compile O(log max_len) prefill shapes, not one per length."""
        import jax.numpy as jnp

        bucket = 8
        while bucket < prompt.size:
            bucket *= 2
        bucket = min(bucket, max_len)
        padded = np.zeros(bucket, np.int32)
        padded[:prompt.size] = prompt
        cache = self._tfm.init_cache(self._cfg, batch=1, max_len=max_len)
        logits, cache = self._prefill(
            self._params, cache, jnp.asarray(padded[None, :]),
            jnp.asarray(prompt.size, jnp.int32))
        self.stats.inc("prefill_dispatches")
        return logits, cache

    def _sampling(self):
        """(top_k, top_p) from custom properties (llamacpp sampler-chain
        parity: same knobs, same order — nucleus before temperature).
        Parsed once: this sits on the per-token host loop."""
        cached = getattr(self, "_sampling_cache", None)
        if cached is None:
            cached = self._sampling_cache = (
                int(self._opts.get("top_k", "0")),
                float(self._opts.get("top_p", "1.0")))
        return cached

    def _sample_host(self, sub, logits, temperature):
        """One host-loop sampling step, via the SAME in-graph helper the
        scanned chunk body uses, so every path draws identical tokens."""
        return self._tfm.sample_logits(sub[None], logits, temperature,
                                       *self._sampling())[:1]

    def _chunk_fn(self, steps: int, temperature: float):
        """Jitted K-step decode chunk, cached per (steps, sampling)."""
        top_k, top_p = self._sampling()
        key = (steps, float(temperature), top_k, top_p)
        fn = self._chunk_jits.get(key)
        if fn is None:
            import jax
            tfm, cfg = self._tfm, self._cfg
            fn = jax.jit(lambda p, c, l, k, a: tfm.decode_chunk_multi(
                p, c, l, k, a, cfg, steps=steps, temperature=temperature,
                top_k=top_k, top_p=top_p))
            self._chunk_jits[key] = fn
        return fn

    def _generate(self, prompt: np.ndarray, emit) -> None:
        import jax
        import jax.numpy as jnp

        prompt = np.asarray(prompt).reshape(-1)
        max_tokens = int(self._opts.get("max_tokens", "16"))
        temperature = float(self._opts.get("temperature", "0"))
        # the DEFAULT max_len derives from the bucket (not the raw
        # prompt length) so the cache shape — and with it the
        # decode-step compilation — is bucket-stable too
        bucket = 8
        while bucket < max(prompt.size, 1):
            bucket *= 2
        max_len = int(self._opts.get("max_len", str(bucket + max_tokens)))
        key = jax.random.PRNGKey(int(self._opts.get("seed", "0")))
        self._check_prompt(prompt, max_len)
        logits, cache = self._prefill_prompt(prompt, max_len)
        pos = prompt.size  # host-side cache index: no per-token device sync
        if self._chunk > 1:
            self._generate_chunked(logits, cache, pos, max_tokens, max_len,
                                   temperature, key, emit)
            return
        for i in range(max_tokens):
            if self._stop.is_set():
                return
            if temperature > 0:
                key, sub = jax.random.split(key)
                tok = self._sample_host(sub, logits, temperature)
            else:
                tok = jnp.argmax(logits, -1)
            emit(np.asarray(tok, np.int32))
            if i + 1 >= max_tokens or pos >= max_len:
                return  # nothing left to decode: skip the trailing step
            logits, cache = self._decode(self._params, cache,
                                         tok.astype(jnp.int32))
            self.stats.add(decode_dispatches=1, decode_steps=1)
            pos += 1

    def _generate_chunked(self, logits, cache, pos, max_tokens, max_len,
                          temperature, key, emit) -> None:
        """Single-stream chunked decode: [chunk] tokens per dispatch and
        per host fetch. Emits the exact token stream of the per-token
        loop (same key-split order, same capacity cutoff at max_len)."""
        import jax
        import jax.numpy as jnp

        mcache = {"k": cache["k"], "v": cache["v"],
                  "index": jnp.broadcast_to(cache["index"], (1,))}
        keys = key[None]
        active = jnp.ones((1,), bool)
        remaining = max_tokens
        while remaining > 0 and not self._stop.is_set():
            # each scan step samples THEN decodes; decode writes at the
            # stream's cache index, legal while index <= max_len-1
            k = min(self._chunk, remaining, max_len - pos)
            if k <= 0:
                # cache full: the per-token loop still emits one final
                # sampled token before stopping — mirror it, no decode
                if temperature > 0:
                    key2, sub = jax.random.split(keys[0])
                    tok = self._sample_host(sub, logits, temperature)
                else:
                    tok = jnp.argmax(logits, -1)
                emit(np.asarray(tok, np.int32))
                return
            toks, logits, mcache, keys = self._chunk_fn(k, temperature)(
                self._params, mcache, logits, keys, active)
            self.stats.add(decode_dispatches=1, decode_steps=k)
            toks_host = np.asarray(toks)  # ONE fetch for k tokens
            for j in range(k):
                emit(toks_host[j].astype(np.int32))
            pos += k
            remaining -= k

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        """Sync path: return the whole generation as one int32 tensor."""
        tokens: List[np.ndarray] = []
        self._generate(np.asarray(inputs[0]), tokens.append)
        return [np.concatenate(tokens) if tokens
                else np.zeros((0,), np.int32)]

    def invoke_async(self, inputs: Sequence[Any], ctx: Any = None) -> None:
        """1-in/N-out: one output frame per generated token, each
        dispatched with this invoke's ``ctx``."""
        prompt = np.asarray(inputs[0])
        if self._n_parallel > 1:
            # validate on the CALLER's thread so an oversized prompt is a
            # visible invoke error, not a silent scheduler drop
            flat = prompt.reshape(-1)
            self._check_prompt(flat, int(self._opts.get(
                "max_len", str(DEFAULT_BATCH_MAX_LEN))))
            with self._cond:
                rem = None
                if self._recovered is not None:
                    # resurrection: a re-submitted prompt that matches a
                    # snapshotted stream continues where it stopped —
                    # the emitted tokens (already delivered through the
                    # acked session pre-crash) join the prefill context
                    # and only the undelivered remainder is generated
                    rem, flat = self._adopt_recovered_locked(flat)
                self._pending.append((flat, ctx, rem))
                self._cond.notify_all()
                # start-check under the lock: two racing invokes must not
                # spawn two schedulers splitting one slot pool
                if self._sched is None or not self._sched.is_alive():
                    self._sched = threading.Thread(
                        target=self._sched_loop, name="llm-sched",
                        daemon=True)
                    self._sched.start()
            return

        def run():
            try:
                self._generate(
                    prompt, lambda tok: self._dispatch([tok], ctx))
            except Exception:  # noqa: BLE001
                logger.exception("llm generation failed")

        t = threading.Thread(target=run, name="llm-generate", daemon=True)
        self._threads.append(t)
        t.start()

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    def snapshot_state(self, snap_dir) -> Optional[Dict[str, Any]]:
        """Continuous-batching state for a preemption snapshot: per
        stream (queued or mid-generation) the prompt, the tokens already
        emitted, and the remaining budget. The KV cache itself is NOT
        saved — it is recomputed by one prefill over prompt+emitted at
        adoption time (cheaper and version-proof next to dumping a
        device cache). Single-stream mode (n_parallel=1) keeps no
        scheduler state and snapshots nothing."""
        with self._cond:
            pend = [{"prompt": np.asarray(p, np.int32).tolist(),
                     "emitted": [], "remaining": rem}
                    for (p, _ctx, rem) in self._pending]
            act = [{"prompt": s["prompt"].tolist(),
                    "emitted": list(s["emitted"]),
                    "remaining": int(s["remaining"])}
                   for s in (self._streams or [])
                   if s is not None and s["remaining"] > 0]
        if not pend and not act:
            return None
        return {"streams": act + pend}

    def restore_state(self, state, snap_dir) -> None:
        """Stash recovered streams; they are adopted lazily when a
        re-submitted prompt (the client's RESUME-driven resend) matches
        one of them — see invoke_async."""
        with self._cond:
            self._recovered = state

    def _adopt_recovered_locked(self, flat: np.ndarray):
        """Match an incoming prompt against the recovered streams
        (caller holds _cond). On a hit: continuation — the pre-crash
        prompt + already-emitted tokens become the prefill context and
        only the remaining budget is generated. Returns
        (remaining_override, prompt_to_queue)."""
        entries = self._recovered.get("streams") or []
        for i, ent in enumerate(entries):
            if np.array_equal(np.asarray(ent["prompt"], np.int32), flat):
                entries.pop(i)
                if not entries:
                    self._recovered = None
                emitted = np.asarray(ent.get("emitted") or [], np.int32)
                rem = ent.get("remaining")
                if emitted.size:
                    flat = np.concatenate(
                        [flat.astype(np.int32), emitted])
                return rem, flat
        return None, flat

    # -- continuous-batching scheduler (n_parallel > 1) --------------------
    def _sched_loop(self) -> None:
        """Decode M streams per dispatch. Admission: pending prompts are
        prefilled (one bucketed dispatch each) into free cache slots;
        every active slot then advances one token per SHARED decode
        dispatch, and finished slots free up mid-flight for waiting
        prompts — continuous batching, not static batching."""
        try:
            self._sched_body()
        except Exception:  # noqa: BLE001 — daemon thread: log, don't die silent
            logger.exception("llm scheduler failed; in-flight streams lost")

    def _sched_body(self) -> None:
        import jax
        import jax.numpy as jnp

        tfm, cfg = self._tfm, self._cfg
        m = self._n_parallel
        max_tokens = int(self._opts.get("max_tokens", "16"))
        max_len = int(self._opts.get("max_len", str(DEFAULT_BATCH_MAX_LEN)))
        temperature = float(self._opts.get("temperature", "0"))
        seed = int(self._opts.get("seed", "0"))
        cache = tfm.init_cache_multi(cfg, batch=m, max_len=max_len)
        logits = jnp.zeros((m, cfg.vocab), jnp.float32)
        tok = jnp.zeros((m,), jnp.int32)
        streams: List[Optional[Dict[str, Any]]] = [None] * m
        with self._cond:
            self._streams = streams  # published for snapshot_state
        while not self._stop.is_set():
            # -- admit pending prompts into free slots
            with self._cond:
                while all(s is None for s in streams) and not self._pending \
                        and not self._stop.is_set():
                    self._cond.wait(0.1)
                if self._stop.is_set():
                    return
                admit = []
                for slot in range(m):
                    if streams[slot] is None and self._pending:
                        admit.append((slot, *self._pending.pop(0)))
            for slot, prompt, ctx, rem in admit:
                try:
                    self._check_prompt(prompt, max_len)
                    l1, c1 = self._prefill_prompt(prompt, max_len)
                except Exception:  # noqa: BLE001 — drop THIS prompt only
                    logger.exception("llm: prompt rejected at admission")
                    continue
                cache = self._insert(cache, c1, jnp.asarray(slot, jnp.int32))
                logits = logits.at[slot].set(l1[0])
                # per-stream PRNG key: the sample sequence matches the
                # n_parallel=1 path for the same seed, independent of
                # which other prompts happen to be in flight. rem
                # overrides the budget for a stream adopted from a
                # preemption snapshot (the rest was emitted pre-crash).
                streams[slot] = {"ctx": ctx,
                                 "remaining": (max_tokens if rem is None
                                               else int(rem)),
                                 "pos": int(prompt.size),
                                 "prompt": np.asarray(prompt,
                                                      np.int32).copy(),
                                 "emitted": [],
                                 "key": jax.random.PRNGKey(seed)}
            active_np = np.array([s is not None for s in streams])
            if not active_np.any():
                continue
            if self._chunk > 1:
                logits, cache = self._sched_chunk(
                    streams, active_np, logits, cache, max_len, temperature)
                continue
            # -- sample on device, D2H just the M token ids
            if temperature > 0:
                subs = []
                for s in streams:
                    if s is None:
                        subs.append(jax.random.PRNGKey(0))
                        continue
                    s["key"], sub = jax.random.split(s["key"])
                    subs.append(sub)
                tok = self._tfm.sample_logits(
                    jnp.stack(subs), logits, temperature, *self._sampling())
            else:
                tok = jnp.argmax(logits, -1)
            tok = tok.astype(jnp.int32)
            tok_host = np.asarray(tok)
            for slot, s in enumerate(streams):
                if s is None:
                    continue
                self._dispatch([tok_host[slot:slot + 1]], s["ctx"])
                with self._cond:
                    # bookkeeping under _cond: a preemption snapshot
                    # reads (prompt, emitted, remaining) coherently
                    s["emitted"].append(int(tok_host[slot]))
                    s["remaining"] -= 1
                    s["pos"] += 1
                # pos is one past the next decode's cache-write position
                # (the write lands at pos-1), so the stream survives
                # while pos <= max_len — matching the single-stream
                # loop's emit-then-check ordering exactly
                if s["remaining"] <= 0 or s["pos"] > max_len:
                    streams[slot] = None
                    # keep the mask current: a lane that just finished
                    # must not keep writing/advancing its cache in the
                    # trailing decode (decode_step_multi also position-
                    # guards at max_len)
                    active_np[slot] = False
            if active_np.any():
                logits, cache = self._decode_multi(
                    self._params, cache, tok, jnp.asarray(active_np))
                self.stats.add(decode_dispatches=1, decode_steps=1)

    def _sched_chunk(self, streams, active_np, logits, cache, max_len,
                     temperature):
        """One chunked round of the continuous-batching loop: K
        sample+decode steps in ONE dispatch, K tokens per stream per
        host fetch. K adapts to the deepest stream still running, so a
        stream never emits past its budget; streams that finish
        mid-chunk have their surplus lane tokens discarded (their lanes
        compute garbage either way). New prompts admit between chunks —
        the admission-latency/throughput knob is ``custom=chunk:K``."""
        import jax
        import jax.numpy as jnp

        # emits each stream still owes; K serves the deepest one fully.
        # The +1 is the capacity tail: the final token a lane emits at
        # pos == max_len is sampled in-scan from the last legal decode's
        # logits — the decode that FOLLOWS that sample is position-
        # guarded inside decode_step_multi (pos < max_len), so it cannot
        # clamp a write onto row max_len-1 (the single-stream invariant
        # of _generate_chunked, enforced in-graph here).
        emits_left = [min(s["remaining"], max_len - s["pos"] + 1)
                      if s else 0 for s in streams]
        k = min(self._chunk, max(emits_left))
        if temperature > 0:
            # one cached filler key for idle slots: a fresh eager
            # PRNGKey per slot per round would cost an RPC each on a
            # remote-attached chip, eroding the chunking win
            if not hasattr(self, "_idle_key"):
                self._idle_key = jax.random.PRNGKey(0)
            keys = jnp.stack([s["key"] if s else self._idle_key
                              for s in streams])
        else:
            keys = jnp.zeros((len(streams), 2), jnp.uint32)
        toks, logits, cache, keys = self._chunk_fn(k, temperature)(
            self._params, cache, logits, keys, jnp.asarray(active_np))
        self.stats.add(decode_dispatches=1, decode_steps=k)
        toks_host = np.asarray(toks)  # [k, M]: ONE fetch for the chunk
        for slot, s in enumerate(streams):
            if s is None:
                continue
            for j in range(min(k, emits_left[slot])):
                self._dispatch([toks_host[j, slot:slot + 1]], s["ctx"])
                with self._cond:
                    s["emitted"].append(int(toks_host[j, slot]))
                    s["remaining"] -= 1
                    s["pos"] += 1
            if temperature > 0:
                s["key"] = keys[slot]
            if s["remaining"] <= 0 or s["pos"] > max_len:
                streams[slot] = None
        return logits, cache


register_alias("llamacpp", "llm")
register_alias("llama2c", "llm")
