"""LLM generative filter — async token streaming on the JAX decode loop.

≙ ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc: 1 prompt in,
N token frames out via the async dispatcher
(nnstreamer_filter_dispatch_output_async, tensor_filter.c:1099-1170).
Here generation is the KV-cache decode loop of models/transformer.py —
static shapes, one jitted decode step reused every token.

model accepts ``zoo://gpt?...`` (zoo spec) or a ``get_lm()`` python file
returning (params, cfg). custom properties (``custom=key:value,...``):
max_tokens, temperature (0 = greedy), seed, max_len.
"""
from __future__ import annotations

import threading
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..tensors.info import TensorsInfo
from ..utils.log import logger
from .base import (FilterFramework, FilterProperties,
                   parse_custom_properties as _parse_custom)
from .registry import register_alias, register_filter


@register_filter
class LlmFilter(FilterFramework):
    NAME = "llm"
    EXTENSIONS = (".gguf",)  # reference auto-detect parity (llamacpp slot)

    def __init__(self):
        self._params = None
        self._cfg = None
        self._decode = None
        self._opts: Dict[str, str] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    def open(self, props: FilterProperties) -> None:
        import jax

        from ..models import transformer as tfm

        model = props.model_files[0] if props.model_files else ""
        if model.startswith("zoo://"):
            parsed = urllib.parse.urlparse(model)
            kwargs = {k: v[0] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
            name = parsed.netloc or parsed.path.lstrip("/")
            if name != "gpt":
                raise ValueError(f"llm filter expects zoo://gpt, got {name}")
            self._cfg = tfm.GPTConfig(
                vocab=int(kwargs.get("vocab", "32000")),
                d_model=int(kwargs.get("d_model", "512")),
                n_heads=int(kwargs.get("n_heads", "8")),
                n_layers=int(kwargs.get("n_layers", "6")))
            self._params = tfm.init_params(
                self._cfg, jax.random.PRNGKey(int(kwargs.get("seed", "0"))))
        elif model.endswith(".py"):
            ns: Dict[str, Any] = {}
            with open(model) as f:
                exec(compile(f.read(), model, "exec"), ns)  # noqa: S102 — user script
            self._params, self._cfg = ns["get_lm"]()
        else:
            raise ValueError(f"llm filter cannot load model {model!r}")
        self._opts = _parse_custom(props.custom_properties)
        cfg = self._cfg

        def step(params, cache, token):
            return tfm.decode_step(params, cache, token, cfg)

        def pre(params, cache, tokens, true_len):
            return tfm.prefill(params, cache, tokens, cfg,
                               true_len=true_len)

        self._decode = jax.jit(step)
        self._prefill = jax.jit(pre)
        self._tfm = tfm
        self._stop.clear()
        # dispatch accounting: prompts of any length must cost ONE
        # prefill dispatch (≙ llamacpp n_batch), then one per token
        self.stats = {"prefill_dispatches": 0, "decode_dispatches": 0}

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        self._params = None
        self._decode = None

    def get_model_info(self):
        # prompt length is per-buffer (dynamic): input derives from caps
        return None, TensorsInfo.make("int32", "1")

    def set_input_info(self, info: TensorsInfo) -> Optional[TensorsInfo]:
        return TensorsInfo.make("int32", "1")

    # -- generation -------------------------------------------------------
    def _generate(self, prompt: np.ndarray, emit) -> None:
        import jax
        import jax.numpy as jnp

        prompt = np.asarray(prompt).reshape(-1)
        if prompt.size == 0:
            raise ValueError("llm: empty prompt")
        max_tokens = int(self._opts.get("max_tokens", "16"))
        temperature = float(self._opts.get("temperature", "0"))
        # prompts pad to power-of-two buckets so streams of varied
        # lengths compile O(log max_len) prefill shapes, not one per
        # length; the DEFAULT max_len is derived from the bucket (not
        # the raw prompt length) so the cache shape — and with it the
        # decode-step compilation — is bucket-stable too
        bucket = 8
        while bucket < prompt.size:
            bucket *= 2
        max_len = int(self._opts.get("max_len", str(bucket + max_tokens)))
        key = jax.random.PRNGKey(int(self._opts.get("seed", "0")))
        if prompt.size > max_len:
            # fail before dispatch: the jitted cache write would raise an
            # opaque XLA shape error (≙ llamacpp context-overflow error)
            raise ValueError(
                f"llm: prompt length {prompt.size} exceeds max_len "
                f"{max_len}; raise custom=max_len:N")
        cache = self._tfm.init_cache(self._cfg, batch=1, max_len=max_len)
        bucket = min(bucket, max_len)
        padded = np.zeros(bucket, np.int32)
        padded[:prompt.size] = prompt
        logits, cache = self._prefill(
            self._params, cache, jnp.asarray(padded[None, :]),
            jnp.asarray(prompt.size, jnp.int32))
        self.stats["prefill_dispatches"] += 1
        pos = prompt.size  # host-side cache index: no per-token device sync
        for i in range(max_tokens):
            if self._stop.is_set():
                return
            if temperature > 0:
                key, sub = jax.random.split(key)
                tok = jax.random.categorical(sub, logits / temperature, -1)
            else:
                tok = jnp.argmax(logits, -1)
            emit(np.asarray(tok, np.int32))
            if i + 1 >= max_tokens or pos >= max_len:
                return  # nothing left to decode: skip the trailing step
            logits, cache = self._decode(self._params, cache,
                                         tok.astype(jnp.int32))
            self.stats["decode_dispatches"] += 1
            pos += 1

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        """Sync path: return the whole generation as one int32 tensor."""
        tokens: List[np.ndarray] = []
        self._generate(np.asarray(inputs[0]), tokens.append)
        return [np.concatenate(tokens) if tokens
                else np.zeros((0,), np.int32)]

    def invoke_async(self, inputs: Sequence[Any]) -> None:
        """1-in/N-out: one output frame per generated token."""
        prompt = np.asarray(inputs[0])

        def run():
            try:
                self._generate(prompt, lambda tok: self._dispatch([tok]))
            except Exception:  # noqa: BLE001
                logger.exception("llm generation failed")

        t = threading.Thread(target=run, name="llm-generate", daemon=True)
        self._threads.append(t)
        t.start()


register_alias("llamacpp", "llm")
register_alias("llama2c", "llm")
