"""pytorch backend: TorchScript (.pt) models.

≙ ext/nnstreamer/tensor_filter/tensor_filter_pytorch.cc (TorchScript
via the libtorch C++ API). Loads with ``torch.jit.load`` and invokes on
the host CPU — like the reference, this is a compatibility backend for
models not yet converted to the XLA path (torch has no TPU device in
this runtime; the jax/tflite/onnx/pb backends own the MXU). Mirroring
the reference, input dimensions must be given by properties or pushed
from negotiated caps (TorchScript carries no static shapes); output
info is probed with one zero-tensor forward at open time when inputs
are known.
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..tensors.info import TensorInfo, TensorsInfo
from ..tensors.types import TensorType
from ..utils.log import logger
from .base import FilterEvent, FilterFramework, FilterProperties
from .registry import register_alias, register_filter


def _have_torch() -> bool:
    # find_spec: availability without importing torch (1-3 s / 100s of
    # MB) at package-import time; the real import happens at open()
    import importlib.util
    return importlib.util.find_spec("torch") is not None


@register_filter
class TorchFilter(FilterFramework):
    NAME = "pytorch"
    EXTENSIONS = (".pt", ".pth")
    AVAILABLE = _have_torch()

    def __init__(self):
        self._module = None
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None
        self._lock = threading.Lock()
        self._path = ""

    def open(self, props: FilterProperties) -> None:
        import torch
        if not props.model_files:
            raise ValueError("pytorch backend needs a model file")
        self._path = props.model_files[0]
        self._module = torch.jit.load(self._path, map_location="cpu")
        self._module.eval()
        self._in_info = props.input_info
        self._out_info = props.output_info
        if self._in_info is not None and self._out_info is None:
            self._out_info = self._probe_outputs(self._in_info)
        logger.info("pytorch backend loaded %s", self._path)

    def close(self) -> None:
        self._module = None

    def _probe_outputs(self, in_info: TensorsInfo) -> TensorsInfo:
        import torch
        zeros = [torch.zeros(tuple(i.shape),
                             dtype=getattr(torch,
                                           np.dtype(i.type.np_dtype).name))
                 for i in in_info]
        with torch.no_grad():
            out = self._module(*zeros)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return TensorsInfo(
            TensorInfo(None, TensorType.from_dtype(
                np.dtype(str(o.dtype).replace("torch.", ""))),
                tuple(o.shape))
            for o in outs)

    def get_model_info(self) -> Tuple[Optional[TensorsInfo],
                                      Optional[TensorsInfo]]:
        return self._in_info, self._out_info

    def set_input_info(self, info: TensorsInfo) -> Optional[TensorsInfo]:
        self._in_info = info
        self._out_info = self._probe_outputs(info)
        return self._out_info

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        import torch
        with self._lock:
            xs = []
            for x, info in zip(inputs, self._in_info or ()):
                arr = np.asarray(x)
                if tuple(arr.shape) != tuple(info.shape):
                    arr = arr.reshape(info.shape)
                xs.append(torch.from_numpy(np.ascontiguousarray(arr)))
            if not xs:  # no declared info: pass through as-is
                xs = [torch.from_numpy(np.ascontiguousarray(np.asarray(x)))
                      for x in inputs]
            with torch.no_grad():
                out = self._module(*xs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o.numpy() for o in outs]

    def handle_event(self, event: FilterEvent, data=None) -> bool:
        if event == FilterEvent.RELOAD_MODEL:
            import torch
            path = (data or {}).get("model_files", (self._path,))[0]
            fresh = torch.jit.load(path, map_location="cpu")
            fresh.eval()
            with self._lock:
                self._module = fresh
                self._path = path
            return True
        return False


register_alias("torch", "pytorch")
