"""nnstreamer_tpu — a TPU-native streaming tensor-pipeline framework.

A ground-up re-design of NNStreamer's capabilities (typed tensor streams,
dataflow pipeline runtime, filter/decoder/converter/trainer subplugins,
among-device stream fan-out) for TPU: compute is cached jax.jit/XLA
executables, activations stay HBM-resident across chained elements, custom
kernels use Pallas, and distribution rides ICI/DCN via jax.sharding instead
of TCP/MQTT. See SURVEY.md for the reference blueprint.
"""

__version__ = "0.1.0"

from .tensors import (Buffer, Caps, Chunk, TensorFormat, TensorInfo,
                      TensorsConfig, TensorsInfo, TensorType)
from .pipeline import Pipeline, parse_launch, make_element, register_element
from . import elements  # noqa: F401  (registers tensor_* elements)
from . import filters  # noqa: F401  (registers filter backends)
from .filters import register_custom_easy
from .single import SingleShot
from .fault import (CircuitBreaker, ErrorPolicy, FaultInjected,
                    TransientError, register_fatal, register_transient)
from .checkpoint import (PreemptGuard, SnapshotError, SnapshotStore,
                         install_sigterm)
from .fleet import (Autoscaler, AutoscalerConfig, BlueGreenRollout,
                    ReplicaSpec, rollout)

__all__ = [
    "Buffer", "Chunk", "Caps", "TensorInfo", "TensorsInfo", "TensorsConfig",
    "TensorType", "TensorFormat", "Pipeline", "parse_launch", "make_element",
    "register_element", "register_custom_easy", "SingleShot", "__version__",
    "CircuitBreaker", "ErrorPolicy", "FaultInjected", "TransientError",
    "register_fatal", "register_transient",
    "SnapshotStore", "SnapshotError", "PreemptGuard", "install_sigterm",
    "Autoscaler", "AutoscalerConfig", "BlueGreenRollout", "ReplicaSpec",
    "rollout",
]
