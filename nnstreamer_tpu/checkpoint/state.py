"""Picklable state helpers shared by element snapshot hooks.

A :class:`~nnstreamer_tpu.tensors.buffer.Buffer` may hold
device-resident ``jax.Array`` chunks and in-flight D2H fetches —
neither pickles. :func:`dump_buffer` materializes every chunk to a
host ndarray and keeps only the picklable frame metadata;
:func:`load_buffer` rebuilds an equivalent host-resident buffer (a
restored frame re-enters the pipeline like any converter output and
migrates back to device on first use).
"""
from __future__ import annotations

from typing import Dict, List

from ..tensors.buffer import Buffer, BufferFlags, Chunk


def dump_buffer(buf: Buffer) -> Dict:
    return {"arrays": [c.host() for c in buf.chunks],
            "pts": buf.pts, "dts": buf.dts, "duration": buf.duration,
            "flags": int(buf.flags), "extras": dict(buf.extras)}


def load_buffer(d: Dict) -> Buffer:
    buf = Buffer([Chunk(a) for a in d["arrays"]], pts=d.get("pts"),
                 dts=d.get("dts"), duration=d.get("duration"),
                 flags=BufferFlags(int(d.get("flags", 0))))
    buf.extras = dict(d.get("extras") or {})
    return buf


def dump_buffers(bufs) -> List[Dict]:
    return [dump_buffer(b) for b in bufs]


def load_buffers(dumps) -> List[Buffer]:
    return [load_buffer(d) for d in dumps]


# -- content addressing ------------------------------------------------

def token_sha(tokens) -> str:
    """Canonical sha256 hex digest of a token sequence.

    The ONE hashing convention shared by the LLM snapshot re-adoption
    path (match a resent prompt to a recovered stream without holding
    the full token array comparison) and the paged KV prefix cache's
    block chain (filters/kvpool.py): int32 little-endian token ids,
    hashed in order. Keeping it here means a snapshot written by one
    replica always matches the digest a resurrected replica computes.
    """
    import hashlib

    import numpy as np

    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32).ravel())
    if arr.dtype.byteorder == ">":  # big-endian host: normalize
        arr = arr.astype("<i4")
    return hashlib.sha256(arr.tobytes()).hexdigest()
