"""Crash-consistent snapshot store for pipeline checkpoints.

Layout (one snapshot = one directory, atomically published)::

    <root>/
      snap-00000007/
        MANIFEST.json          # version, seq, meta, per-file sha256+size
        elements/<name>.blob   # pickled element state dict
        elements/<name>.d/...  # optional per-element scratch files
                               # (e.g. the trainer's orbax params tree)

Crash consistency: a snapshot is written into a ``.tmp-*`` sibling,
every file is hashed into the manifest, the manifest is fsynced, and
the directory is published with a single :func:`os.replace` — a
reader either sees a complete, self-verifying snapshot or nothing.
A crash mid-write leaves only a ``.tmp-*`` directory, which the next
writer sweeps.

Integrity: :meth:`SnapshotStore.verify` re-hashes every manifest
entry; a truncated blob or tampered manifest raises
:class:`SnapshotError` carrying ``.blob`` — the relative path of the
offending file — so a restore can *name* what it rejected instead of
silently proceeding with partial state.

Retention: ``retain`` newest snapshots survive ``save()``; older
ones are garbage-collected (oldest first).
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Callable, Dict, List, Optional

MANIFEST = "MANIFEST.json"
FORMAT_VERSION = 1
_SNAP_RE = re.compile(r"^snap-(\d{8})$")


class SnapshotError(RuntimeError):
    """A snapshot failed verification. ``blob`` names the offending
    file (relative to the snapshot directory) — ``MANIFEST.json`` when
    the manifest itself is missing or tampered."""

    def __init__(self, message: str, blob: Optional[str] = None):
        super().__init__(message)
        self.blob = blob


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_rel(root: str) -> List[str]:
    out: List[str] = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(out)


class SnapshotStore:
    """Retain-N crash-consistent snapshot directory manager."""

    def __init__(self, root: str, retain: int = 3):
        self.root = root
        self.retain = max(1, int(retain))

    # -- write -------------------------------------------------------------
    def save(self, writer: Callable[[str], None],
             meta: Optional[Dict] = None) -> str:
        """Run ``writer(tmp_dir)`` to populate a fresh snapshot, seal it
        with a hashed manifest, and publish it atomically. Returns the
        final snapshot directory path."""
        os.makedirs(self.root, exist_ok=True)
        self._sweep_tmp()
        seq = self._next_seq()
        tmp = os.path.join(self.root, f".tmp-snap-{seq:08d}-{os.getpid()}")
        os.makedirs(tmp)
        try:
            writer(tmp)
            files = {rel: {"sha256": _sha256(os.path.join(tmp, rel)),
                           "size": os.path.getsize(os.path.join(tmp, rel))}
                     for rel in _walk_rel(tmp) if rel != MANIFEST}
            manifest = {"version": FORMAT_VERSION, "seq": seq,
                        "meta": dict(meta or {}), "files": files}
            mpath = os.path.join(tmp, MANIFEST)
            with open(mpath, "w", encoding="utf-8") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            final = os.path.join(self.root, f"snap-{seq:08d}")
            os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        return final

    # -- read --------------------------------------------------------------
    def snapshots(self) -> List[str]:
        """Published snapshot directories, oldest first."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        snaps = sorted(n for n in names if _SNAP_RE.match(n))
        return [os.path.join(self.root, n) for n in snaps]

    def latest(self) -> Optional[str]:
        snaps = self.snapshots()
        return snaps[-1] if snaps else None

    @staticmethod
    def verify(snap_dir: str) -> Dict:
        """Re-hash every manifest entry; return the parsed manifest.
        Raises :class:`SnapshotError` (with ``.blob``) on any missing,
        truncated, or tampered file — never a silent partial pass."""
        mpath = os.path.join(snap_dir, MANIFEST)
        try:
            with open(mpath, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as exc:
            raise SnapshotError(
                f"snapshot manifest unreadable: {mpath}: {exc}",
                blob=MANIFEST) from exc
        if (not isinstance(manifest, dict)
                or manifest.get("version") != FORMAT_VERSION
                or not isinstance(manifest.get("files"), dict)):
            raise SnapshotError(
                f"snapshot manifest malformed or wrong version: {mpath}",
                blob=MANIFEST)
        for rel, ent in sorted(manifest["files"].items()):
            path = os.path.join(snap_dir, rel)
            try:
                size = os.path.getsize(path)
            except OSError as exc:
                raise SnapshotError(
                    f"snapshot blob missing: {rel}", blob=rel) from exc
            if size != ent.get("size"):
                raise SnapshotError(
                    f"snapshot blob truncated: {rel} "
                    f"({size} != {ent.get('size')} bytes)", blob=rel)
            if _sha256(path) != ent.get("sha256"):
                raise SnapshotError(
                    f"snapshot blob corrupt: {rel} (sha256 mismatch)",
                    blob=rel)
        return manifest

    # -- housekeeping ------------------------------------------------------
    def _next_seq(self) -> int:
        last = 0
        for path in self.snapshots():
            m = _SNAP_RE.match(os.path.basename(path))
            if m:
                last = max(last, int(m.group(1)))
        return last + 1

    def _gc(self) -> None:
        snaps = self.snapshots()
        for path in snaps[:-self.retain]:
            shutil.rmtree(path, ignore_errors=True)

    def _sweep_tmp(self) -> None:
        # a crash mid-save leaves .tmp-* orphans; they are never visible
        # to readers, so sweeping them is always safe
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for n in names:
            if n.startswith(".tmp-snap-"):
                shutil.rmtree(os.path.join(self.root, n),
                              ignore_errors=True)
