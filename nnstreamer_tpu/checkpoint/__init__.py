"""Pipeline-wide checkpoint/restore: crash-consistent snapshots of
every stateful element, SIGTERM drain-and-snapshot, and resume.

Three pieces (see ``Documentation/robustness.md`` — "surviving
preemption"):

- the ``Checkpointable`` element contract
  (``Element.snapshot_state()/restore_state()``, advertised by the
  ``CHECKPOINTABLE`` doc attribute) implemented by every stateful
  element — trainer, aggregator, repo, LLM continuous batching, serve
  scheduler ledger, edge session rings;
- :class:`SnapshotStore` — write-temp + hashed manifest + atomic
  rename + retain-N GC; :meth:`~SnapshotStore.verify` rejects a
  truncated or tampered snapshot with a :class:`SnapshotError` naming
  the bad blob;
- the preemption path — ``Pipeline.preempt(grace_s, dir)`` (quiesce →
  bounded drain → snapshot → stop, degrading to snapshot-without-drain
  under a short grace with abandoned frames *declared*), wired to
  SIGTERM by :class:`~nnstreamer_tpu.fault.preempt.PreemptGuard`, and
  ``Pipeline.restore(dir)`` rebuilding element state before
  ``start()``.
"""
from ..fault.preempt import PreemptGuard, install_sigterm
from .store import MANIFEST, SnapshotError, SnapshotStore

__all__ = ["SnapshotStore", "SnapshotError", "MANIFEST",
           "PreemptGuard", "install_sigterm"]
