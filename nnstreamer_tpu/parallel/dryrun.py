"""Multi-chip training-step dryrun, runnable in-process or as a child.

One sharded training step (forward + backward + optimizer, ring attention
when a seq axis exists) on an ``n_devices`` mesh of virtual CPU devices.
The driver uses this to validate the dp/sp/tp sharding story compiles and
executes without real multi-chip hardware.

Designed to be robust to process state: ``ensure_devices`` forces the CPU
platform *before* the first backend initialization; if JAX has already
initialized on another platform (e.g. the tunneled TPU), callers must run
:func:`run` in a fresh subprocess instead (``__graft_entry__`` does this).
"""
from __future__ import annotations

import os
import re
import sys

_SUBPROCESS_HINT = (
    "run the dryrun in a fresh subprocess instead: "
    "`python -m nnstreamer_tpu.parallel.dryrun <n>` "
    "(what __graft_entry__.dryrun_multichip does)")


def _backend_initialized() -> bool:
    """True once a JAX backend exists in this process — from then on
    XLA_FLAGS edits and jax_platforms flips are silent no-ops."""
    if sys.modules.get("jax") is None:
        return False
    try:
        from jax._src import xla_bridge
    except ImportError:  # pragma: no cover - very old jax layout
        return False
    if hasattr(xla_bridge, "backends_are_initialized"):
        return bool(xla_bridge.backends_are_initialized())
    return bool(getattr(xla_bridge, "_backends", None))


def ensure_devices(n_devices: int) -> None:
    """Make >= n_devices JAX devices available, or raise.

    Must be called before JAX initializes a backend in this process.
    Afterwards the device-count flag cannot take effect any more, so
    instead of silently no-opping (and failing later with a confusing
    device count) this raises a RuntimeError naming the subprocess
    fallback.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"xla_force_host_platform_device_count=(\d+)", flags)
    forced = int(m.group(1)) if m else 0
    if forced < n_devices and _backend_initialized():
        raise RuntimeError(
            f"ensure_devices({n_devices}): a JAX backend is already "
            f"initialized in this process with "
            f"xla_force_host_platform_device_count={forced or 'unset'}, "
            f"and the flag is a silent no-op after initialization — "
            + _SUBPROCESS_HINT)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={n_devices}").strip()
    import jax

    # The dryrun always wants the virtual CPU mesh (one real TPU chip can
    # never satisfy n_devices). A sitecustomize may have force-set
    # jax_platforms to the tunneled TPU via config.update — which overrides
    # JAX_PLATFORMS — so flip it back BEFORE the first jax.devices() call;
    # after a backend initializes the flip is a silent no-op (hence the
    # subprocess fallback in __graft_entry__.dryrun_multichip).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        have = len(jax.devices())
    except RuntimeError:
        have = 0
    if have < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have {have}")


def run(n_devices: int) -> float:
    """One sharded train step on an n-device mesh (dp x sp x tp)."""
    ensure_devices(n_devices)
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from nnstreamer_tpu.models import transformer as tfm
    from nnstreamer_tpu.parallel import GPT_RULES
    from nnstreamer_tpu.parallel.mesh import best_mesh
    from nnstreamer_tpu.parallel.train import (create_train_state,
                                               make_train_step, shard_batch)

    mesh = best_mesh(n_devices)
    dp, sp, tp = (mesh.shape[a] for a in mesh.axis_names)
    cfg = tfm.GPTConfig(vocab=256, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, mesh=mesh,
                        seq_axis="seq" if sp > 1 else None)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    optimizer = optax.adamw(1e-3)
    state = create_train_state(params, optimizer, mesh, GPT_RULES)

    seq = 8 * sp  # divisible by the seq axis for ring attention blocks
    batch = jnp.zeros((2 * dp, seq + 1), jnp.int32)
    batch = shard_batch(batch, mesh, P("data", None))

    step = make_train_step(lambda p, b: tfm.loss_fn(p, b, cfg), optimizer)
    state, loss = step(state, batch)
    loss.block_until_ready()
    assert jnp.isfinite(loss), f"non-finite loss {loss}"
    schemes = "ring"
    if sp > 1 and (cfg.n_heads // tp) % sp == 0:
        # same step through the OTHER sequence-parallel scheme, so the
        # driver validates both collective patterns compile + execute
        import dataclasses
        cfg_u = dataclasses.replace(cfg, seq_axis="seq",
                                    seq_scheme="ulysses")
        loss_u = tfm.loss_fn(state.params, batch, cfg_u)
        loss_u.block_until_ready()
        assert jnp.isfinite(loss_u), f"non-finite ulysses loss {loss_u}"
        schemes = "ring+ulysses"
    print(f"dryrun_multichip: mesh dp={dp} sp={sp} tp={tp} "
          f"seq={schemes} loss={float(loss):.4f} train ok", flush=True)
    run_infer(n_devices)
    return float(loss)


def run_infer(n_devices: int) -> None:
    """Sharded *inference* round on the same virtual mesh (VERDICT r4
    item 5 — the BASELINE config-5 story): several query clients stream
    distinct frames to ONE server whose serversrc micro-batches them
    (batch=4) into shared stacked invokes of a mesh-mode mobilenet
    (batch dim on the ``data`` axis, params placed by rule table), and
    the serversink row-routes replies back. Asserts (a) micro-batching
    actually happened (< one invoke per frame and a stacked signature
    compiled), (b) every client got ITS OWN frames' answers, in order,
    bit-matching a single-device reference."""
    ensure_devices(n_devices)
    import socket
    import threading
    import time

    import numpy as np

    from nnstreamer_tpu import Buffer, parse_launch
    from nnstreamer_tpu.filters import FilterProperties, find_filter

    size = 96  # real conv stack, sized for the virtual CPU mesh
    zoo = f"zoo://mobilenet_v2?size={size}"
    caps = ('"other/tensors,format=static,num_tensors=1,'
            f'types=(string)uint8,dimensions=(string)3:{size}:{size},'
            'framerate=(fraction)0/1"')
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    dp = max(1, n_devices // 2)
    server = parse_launch(
        f"tensor_query_serversrc name=qs port={port} id=42 batch=4 "
        f"! tensor_filter name=f framework=jax model={zoo} "
        f'custom="mesh:{dp}x1x2" prefetch-host=true '
        f"! tensor_query_serversink id=42")
    server.start()
    time.sleep(0.2)

    ref = find_filter("jax")()
    ref.open(FilterProperties(framework="jax", model_files=(zoo,)))
    n_clients, frames_each = 3, 4
    rng = np.random.default_rng(7)
    xs = {(c, i): rng.integers(0, 255, (size, size, 3), np.uint8,
                               endpoint=True)
          for c in range(n_clients) for i in range(frames_each)}
    want = {k: np.asarray(ref.invoke([v])[0]) for k, v in xs.items()}
    ref.close()

    results: dict = {}

    def client(c):
        # jittered starts: clients must interleave mid-stream (not line
        # up batch-aligned), so the order assertion below exercises the
        # row router against mixed-client batches
        time.sleep(0.03 * c)
        cl = parse_launch(
            f"appsrc name=in caps={caps} "
            f"! tensor_query_client port={port} timeout=60 max-request=8 "
            "! appsink name=out")
        cl.start()
        for i in range(frames_each):
            cl["in"].push_buffer(Buffer.from_arrays([xs[(c, i)]]))
        deadline = time.monotonic() + 300
        while len(cl["out"].buffers) < frames_each \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        results[c] = [np.asarray(b.chunks[0].host()).copy()
                      for b in cl["out"].buffers]
        cl["in"].end_stream()
        cl.stop()

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=320)
    n_invokes = server["f"]._invoke_count
    sigs = list(server["f"].fw._jit_cache)
    server.stop()
    total = n_clients * frames_each
    import math
    # a perfectly coalescing server needs ceil(total/4) stacked
    # invokes; +2 tolerates ragged head/tail batches from the jittered
    # client starts. More than that means micro-batching degraded to
    # near-per-frame dispatch (the regression this guard exists for).
    bound = math.ceil(total / 4) + 2
    assert n_invokes <= bound, \
        f"micro-batching degraded: {n_invokes} invokes for {total} " \
        f"frames (bound {bound})"
    assert any(sig and sig[0][0] and sig[0][0][0] == 4 for sig in sigs), \
        f"no stacked (batch=4) signature compiled: {sigs}"
    for c in range(n_clients):
        got = results.get(c, [])
        assert len(got) == frames_each, \
            f"client {c} got {len(got)}/{frames_each} replies"
        for i, arr in enumerate(got):
            np.testing.assert_allclose(
                arr, want[(c, i)], rtol=1e-4, atol=1e-4,
                err_msg=f"row-routing broke for client {c} frame {i}")
    print(f"dryrun_multichip: mesh dp={dp} tp=2 query micro-batch=4 "
          f"clients={n_clients} invokes={n_invokes}/{total} "
          "row-routing infer ok", flush=True)


if __name__ == "__main__":  # python -m nnstreamer_tpu.parallel.dryrun N
    import sys

    run(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
