"""Multi-chip training-step dryrun, runnable in-process or as a child.

One sharded training step (forward + backward + optimizer, ring attention
when a seq axis exists) on an ``n_devices`` mesh of virtual CPU devices.
The driver uses this to validate the dp/sp/tp sharding story compiles and
executes without real multi-chip hardware.

Designed to be robust to process state: ``ensure_devices`` forces the CPU
platform *before* the first backend initialization; if JAX has already
initialized on another platform (e.g. the tunneled TPU), callers must run
:func:`run` in a fresh subprocess instead (``__graft_entry__`` does this).
"""
from __future__ import annotations

import os


def ensure_devices(n_devices: int) -> None:
    """Make >= n_devices JAX devices available, or raise.

    Must be called before JAX initializes a backend in this process —
    afterwards ``jax_platforms`` flips are silent no-ops.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={n_devices}").strip()
    import jax

    # The dryrun always wants the virtual CPU mesh (one real TPU chip can
    # never satisfy n_devices). A sitecustomize may have force-set
    # jax_platforms to the tunneled TPU via config.update — which overrides
    # JAX_PLATFORMS — so flip it back BEFORE the first jax.devices() call;
    # after a backend initializes the flip is a silent no-op (hence the
    # subprocess fallback in __graft_entry__.dryrun_multichip).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        have = len(jax.devices())
    except RuntimeError:
        have = 0
    if have < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have {have}")


def run(n_devices: int) -> float:
    """One sharded train step on an n-device mesh (dp x sp x tp)."""
    ensure_devices(n_devices)
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from nnstreamer_tpu.models import transformer as tfm
    from nnstreamer_tpu.parallel import GPT_RULES
    from nnstreamer_tpu.parallel.mesh import best_mesh
    from nnstreamer_tpu.parallel.train import (create_train_state,
                                               make_train_step, shard_batch)

    mesh = best_mesh(n_devices)
    dp, sp, tp = (mesh.shape[a] for a in mesh.axis_names)
    cfg = tfm.GPTConfig(vocab=256, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, mesh=mesh,
                        seq_axis="seq" if sp > 1 else None)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    optimizer = optax.adamw(1e-3)
    state = create_train_state(params, optimizer, mesh, GPT_RULES)

    seq = 8 * sp  # divisible by the seq axis for ring attention blocks
    batch = jnp.zeros((2 * dp, seq + 1), jnp.int32)
    batch = shard_batch(batch, mesh, P("data", None))

    step = make_train_step(lambda p, b: tfm.loss_fn(p, b, cfg), optimizer)
    state, loss = step(state, batch)
    loss.block_until_ready()
    assert jnp.isfinite(loss), f"non-finite loss {loss}"
    schemes = "ring"
    if sp > 1 and (cfg.n_heads // tp) % sp == 0:
        # same step through the OTHER sequence-parallel scheme, so the
        # driver validates both collective patterns compile + execute
        import dataclasses
        cfg_u = dataclasses.replace(cfg, seq_axis="seq",
                                    seq_scheme="ulysses")
        loss_u = tfm.loss_fn(state.params, batch, cfg_u)
        loss_u.block_until_ready()
        assert jnp.isfinite(loss_u), f"non-finite ulysses loss {loss_u}"
        schemes = "ring+ulysses"
    print(f"dryrun_multichip: mesh dp={dp} sp={sp} tp={tp} "
          f"seq={schemes} loss={float(loss):.4f} ok", flush=True)
    return float(loss)


if __name__ == "__main__":  # python -m nnstreamer_tpu.parallel.dryrun N
    import sys

    run(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
