"""Device-mesh construction helpers.

The mesh axes follow the scaling-book convention: ``data`` (batch /
fully-replicated gradients via psum), ``seq`` (sequence/context
parallelism — ring attention neighbors should be ICI neighbors), and
``model`` (tensor parallelism). Multi-host meshes come from
``jax.devices()`` spanning hosts; XLA routes collectives over ICI within a
slice and DCN across slices.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXES = ("data", "seq", "model")

# one Mesh object per (logical shape, device set): a serving filter and a
# colocated trainer declaring the same spec get the SAME mesh — one device
# pool, two workloads, neither evicting the other's params (train/serve
# colocation). Mesh is immutable, so sharing is safe across threads.
_SHARED: Dict[Tuple, Mesh] = {}
_SHARED_LOCK = threading.Lock()


def make_mesh(shape: Sequence[int], axis_names: Sequence[str] = AXES,
              devices=None) -> Mesh:
    """Mesh of the given logical shape; devices default to all local."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh {tuple(shape)} needs {n} devices, "
                         f"have {len(devices)}")
    try:
        arr = mesh_utils.create_device_mesh(tuple(shape), devices[:n])
    except Exception:  # CPU/virtual devices: no topology info, plain reshape
        arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def spec_dims(spec: str) -> Optional[Tuple[int, int, int]]:
    """Parse an explicit ``"DxSxT"`` spec into (dp, sp, tp) without
    touching devices; None for ``auto``/``true``/empty (device-count
    dependent) or anything unparseable."""
    if not spec or spec in ("auto", "true"):
        return None
    try:
        dims = [int(d) for d in str(spec).lower().split("x")]
    except ValueError:
        return None
    if not dims or any(d < 1 for d in dims):
        return None
    while len(dims) < 3:
        dims.append(1)
    return tuple(dims[:3])  # type: ignore[return-value]


def spec_dp(spec: str) -> int:
    """The data-parallel factor a spec declares: parsed statically for
    explicit specs (no device access — safe for lint/admission code);
    ``auto`` consults the backend via :func:`best_mesh`; anything empty
    or unparseable is 1 (no snapping, no sharding)."""
    dims = spec_dims(spec)
    if dims is not None:
        return dims[0]
    if spec in ("auto", "true"):
        try:
            return factorization(best_mesh())[0]
        except Exception:  # noqa: BLE001 — no backend: degrade to unsharded
            return 1
    return 1


def mesh_from_spec(spec: str) -> Mesh:
    """Element-property mesh grammar: ``"2x2x2"`` -> Mesh(dp=2, sp=2,
    tp=2); missing trailing factors default to 1; ``"auto"``/``"true"``
    factors all visible devices via :func:`best_mesh`. Resolved meshes
    are shared: two elements declaring the same spec over the same
    device set (a serving filter and a colocated trainer, a serve src
    and its downstream filter) get one Mesh object."""
    if spec in ("auto", "true"):
        return shared_mesh(factorization(best_mesh()))
    dims = spec_dims(spec)
    if dims is None:
        raise ValueError(f"unparseable mesh spec {spec!r} "
                         f"(want 'DxSxT', 'auto' or 'true')")
    return shared_mesh(dims)


def shared_mesh(dims: Sequence[int]) -> Mesh:
    """The process-wide shared Mesh for a logical shape over the default
    device set (see module docstring on colocation)."""
    dims = tuple(int(d) for d in dims)
    key = (dims, tuple((d.platform, d.id) for d in jax.devices()))
    with _SHARED_LOCK:
        mesh = _SHARED.get(key)
        if mesh is None:
            mesh = _SHARED[key] = make_mesh(dims)
        return mesh


def best_mesh(n_devices: Optional[int] = None, model_parallel: int = 0,
              seq_parallel: int = 0) -> Mesh:
    """Factor n into (data, seq, model).

    Defaults: model axis gets 2 when n is even (exercises tp collectives),
    seq gets 2 when 4 | n, data takes the rest. Explicit sizes override.
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    tp = model_parallel or (2 if n % 2 == 0 else 1)
    rest = n // tp
    sp = seq_parallel or (2 if rest % 2 == 0 and rest >= 2 else 1)
    dp = rest // sp
    if dp * sp * tp != n:
        raise ValueError(f"cannot factor {n} into dp*sp*tp = {dp}*{sp}*{tp}")
    return make_mesh((dp, sp, tp))


def factorization(mesh: Mesh) -> Tuple[int, int, int]:
    return tuple(mesh.shape[a] for a in mesh.axis_names)  # type: ignore
