"""Device-mesh construction helpers.

The mesh axes follow the scaling-book convention: ``data`` (batch /
fully-replicated gradients via psum), ``seq`` (sequence/context
parallelism — ring attention neighbors should be ICI neighbors), and
``model`` (tensor parallelism). Multi-host meshes come from
``jax.devices()`` spanning hosts; XLA routes collectives over ICI within a
slice and DCN across slices.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXES = ("data", "seq", "model")


def make_mesh(shape: Sequence[int], axis_names: Sequence[str] = AXES,
              devices=None) -> Mesh:
    """Mesh of the given logical shape; devices default to all local."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh {tuple(shape)} needs {n} devices, "
                         f"have {len(devices)}")
    try:
        arr = mesh_utils.create_device_mesh(tuple(shape), devices[:n])
    except Exception:  # CPU/virtual devices: no topology info, plain reshape
        arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def mesh_from_spec(spec: str) -> Mesh:
    """Element-property mesh grammar: ``"2x2x2"`` -> Mesh(dp=2, sp=2,
    tp=2); missing trailing factors default to 1; ``"auto"``/``"true"``
    factors all visible devices via :func:`best_mesh`."""
    if spec in ("auto", "true"):
        return best_mesh()
    dims = [int(d) for d in spec.lower().split("x")]
    while len(dims) < 3:
        dims.append(1)
    return make_mesh(tuple(dims[:3]))


def best_mesh(n_devices: Optional[int] = None, model_parallel: int = 0,
              seq_parallel: int = 0) -> Mesh:
    """Factor n into (data, seq, model).

    Defaults: model axis gets 2 when n is even (exercises tp collectives),
    seq gets 2 when 4 | n, data takes the rest. Explicit sizes override.
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    tp = model_parallel or (2 if n % 2 == 0 else 1)
    rest = n // tp
    sp = seq_parallel or (2 if rest % 2 == 0 and rest >= 2 else 1)
    dp = rest // sp
    if dp * sp * tp != n:
        raise ValueError(f"cannot factor {n} into dp*sp*tp = {dp}*{sp}*{tp}")
    return make_mesh((dp, sp, tp))


def factorization(mesh: Mesh) -> Tuple[int, int, int]:
    return tuple(mesh.shape[a] for a in mesh.axis_names)  # type: ignore
