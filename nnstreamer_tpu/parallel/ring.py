"""Ring attention: exact causal attention with the sequence dim sharded
across a mesh axis.

Long-context is first-class here (the reference handles long prompts only
inside llama.cpp's own context, SURVEY.md §5 "long-context" note; on TPU
sequence parallelism is a framework feature). Each device holds one block
of Q/K/V along the sequence; K/V blocks rotate around the ring via
``jax.lax.ppermute`` (ICI neighbor exchange) while a flash-style online
softmax accumulates the exact result — memory per device stays
O(block²) instead of O(S²).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _block_attend(q, k, v, q_pos, k_pos, o, m, l):
    """One online-softmax accumulation step.

    q: [B,T,H,D]; k/v: [B,T,H,D]; *_pos: [T] global positions;
    carry o: [B,T,H,D] f32, m/l: [B,H,T] f32 running max / denominator.
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = q_pos[None, None, :, None] >= k_pos[None, None, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # fully-masked rows keep m == -inf; guard exp against nan
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(mask, p, 0.0)
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention_local(q, k, v, axis_name: str):
    """Per-shard ring attention body (call inside shard_map).

    q/k/v: local blocks [B, T, H, D]; sequence axis sharded over
    ``axis_name``. Returns the local output block [B, T, H, D].
    """
    idx = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    t = q.shape[1]
    q_pos = idx * t + jnp.arange(t)
    b, _, h, d = q.shape
    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        o, m, l, k_blk, v_blk, src = carry
        k_pos = src * t + jnp.arange(t)
        o, m, l = _block_attend(q, k_blk, v_blk, q_pos, k_pos, o, m, l)
        # rotate: our block moves to the next device; we receive the
        # previous device's (ICI neighbor exchange)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        src = (src - 1) % n
        return (o, m, l, k_blk, v_blk, src), None

    (o, m, l, _, _, _), _ = jax.lax.scan(
        body, (o0, m0, l0, k, v, idx), None, length=n)
    l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys (shouldn't occur causally)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, data_axis: Optional[str],
                           seq_axis: str, model_axis: Optional[str]):
    """shard_map wrapper: q/k/v are global [B,S,H,D] arrays (possibly
    already sharded); B over data, S over seq, heads over model."""
    da = data_axis if data_axis in mesh.axis_names else None
    ma = model_axis if model_axis in mesh.axis_names else None
    spec = P(da, seq_axis, ma, None)

    fn = jax.shard_map(
        partial(ring_attention_local, axis_name=seq_axis),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def dense_reference(q, k, v):
    """Unsharded causal attention for correctness tests."""
    s = q.shape[1]
    pos = jnp.arange(s)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = pos[None, None, :, None] >= pos[None, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
