"""Partition rules: regex-over-param-path -> PartitionSpec.

The t5x/maxtext pattern: a param pytree with stable names, a small rule
table, and NamedShardings derived per mesh. Rules reference logical mesh
axes by name; axes missing from a mesh are dropped (spec entry -> None),
so the same rules serve 1-chip, dp-only, and full dp×sp×tp meshes.
"""
from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rule = Tuple[str, Sequence[Optional[str]]]

# Megatron-style TP for models/transformer.py param names:
# column-parallel in-projections, row-parallel out-projections.
GPT_RULES: List[Rule] = [
    (r"embed$", ("model", None)),     # vocab-sharded embedding
    (r"head$", (None, "model")),
    (r"\bw[qkv]$", (None, "model")),
    (r"\bwo$", ("model", None)),
    (r"\bw[13]$", (None, "model")),
    (r"\bw2$", ("model", None)),
    (r"ln.*|.*scale$|.*bias$", ()),   # norms: replicated
]


def rules_by_name(name: str) -> List[Rule]:
    """Named rule tables for element properties (``rules:gpt``)."""
    tables = {"gpt": GPT_RULES, "none": [], "": []}
    if name not in tables:
        raise ValueError(f"unknown sharding rule table {name!r} "
                         f"(have: {sorted(k for k in tables if k)})")
    return tables[name]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for(path: str, rules: Sequence[Rule],
             mesh_axes: Sequence[str]) -> P:
    for pattern, axes in rules:
        if re.search(pattern, path):
            return P(*(a if a in mesh_axes else None for a in axes))
    return P()  # default: replicate


def pspec_tree(params: Any, rules: Sequence[Rule], mesh: Mesh) -> Any:
    """PartitionSpec pytree matching ``params``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: spec_for(_path_str(path), rules, mesh.axis_names),
        params)


def named_sharding_tree(params: Any, rules: Sequence[Rule], mesh: Mesh) -> Any:
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        pspec_tree(params, rules, mesh))


def shard_params(params: Any, rules: Sequence[Rule], mesh: Mesh) -> Any:
    """Place a param pytree onto the mesh per the rules (H2D reshard)."""
    return jax.device_put(params, named_sharding_tree(params, rules, mesh))


def batch_sharding(mesh: Mesh, ndim: int, batch: int) -> NamedSharding:
    """Batch-major layout for one stacked serve batch: dim 0 split over
    ``data`` when the batch divides the dp degree, replicated otherwise
    (an indivisible batch still runs — every chip sees all rows)."""
    ndp = mesh.shape.get("data", 1)
    if ndim > 0 and ndp > 1 and batch % ndp == 0:
        return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))
    return NamedSharding(mesh, P())


def place_batch(arrays: Sequence[Any], mesh: Mesh) -> List[Any]:
    """device_put a stacked batch onto the mesh batch-major (dim 0 over
    ``data``). Arrays already committed with the wanted sharding pass
    through untouched, so placing upstream of the filter costs nothing
    when the filter re-places."""
    out = []
    for a in arrays:
        want = batch_sharding(mesh, a.ndim, a.shape[0] if a.ndim else 0)
        if isinstance(a, jax.Array) and a.sharding == want:
            out.append(a)
        else:
            out.append(jax.device_put(a, want))
    return out
