"""Sharded training step.

The distributed training-path core: params live sharded on the mesh
(parallel/sharding.py rules), the batch is sharded on the data/seq axes,
``jax.jit`` propagates shardings through grad+optimizer so XLA inserts the
psum/reduce-scatter collectives (scaling-book recipe: annotate inputs, let
GSPMD place collectives on ICI). The pipeline-facing trainer element
(elements/trainer.py) drives this via the trainer-subplugin ABI
(ref: include/nnstreamer_plugin_api_trainer.h).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import Rule, named_sharding_tree


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any  # scalar int32 array

    def tree_flatten(self):  # registered below
        return (self.params, self.opt_state, self.step), None


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, c: TrainState(*c))


def create_train_state(params: Any, optimizer: optax.GradientTransformation,
                       mesh: Optional[Mesh] = None,
                       rules: Optional[Any] = None) -> TrainState:
    """Init optimizer state on-device. With a mesh, params are placed per
    the rules first and a jitted init lets GSPMD shard the moments like
    the params they mirror."""
    if mesh is not None and rules is not None:
        params = jax.device_put(params, named_sharding_tree(params, rules, mesh))
    opt_state = jax.jit(optimizer.init)(params)
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32))


def make_train_step(loss_fn: Callable[[Any, Any], jax.Array],
                    optimizer: optax.GradientTransformation,
                    donate: bool = True,
                    has_aux: bool = False) -> Callable[[TrainState, Any],
                                                       Tuple]:
    """loss_fn(params, batch) -> scalar (or (scalar, aux) with has_aux).
    Returns jitted (state, batch) -> (state, loss[, aux]). Sharding flows
    from the input arrays."""

    def step(state: TrainState, batch) -> Tuple:
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        new = TrainState(params, opt_state, state.step + 1)
        return (new, loss, aux) if has_aux else (new, loss)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def shard_batch(batch, mesh: Mesh, spec: P):
    """Place a host batch onto the mesh (data/seq sharded)."""
    return jax.device_put(batch, NamedSharding(mesh, spec))
