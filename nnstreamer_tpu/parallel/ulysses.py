"""Ulysses-style all-to-all sequence parallelism.

The second of the two standard long-context schemes (the other is ring
attention, parallel/ring.py): instead of rotating K/V blocks around the
ICI ring, every device swaps its sequence shard for a HEAD shard with
one ``all_to_all``, computes ordinary full-sequence attention over its
head slice, and swaps back. Two collectives per layer, each moving
activations once — communication volume is O(S·H·D/n) independent of
the ring's n steps, at the cost of requiring heads % n == 0.

When to use which (both are exact):
  * ring    — heads < devices, or ultra-long S where even one gathered
              head slice [B, S, H/n, D] exceeds memory budget.
  * ulysses — plenty of heads, moderate S: fewer collectives, and the
              attention itself is an unsharded matmul XLA can fuse
              freely (no scan carry).

The reference has no analog (long prompts live inside llama.cpp's own
context, SURVEY.md §5); on TPU sequence parallelism is a framework
feature.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .ring import dense_reference


def ulysses_attention_local(q, k, v, axis_name: str):
    """Per-shard body (call inside shard_map).

    q/k/v: local sequence blocks [B, T, H, D] with S = n·T sharded over
    ``axis_name``; requires H % n == 0. Returns the local [B, T, H, D]
    output block.
    """
    n = jax.lax.psum(1, axis_name)
    b, t, h, d = q.shape

    def seq_to_heads(x):
        # [B, T, H, D] -> exchange: keep H/n heads, gain full sequence.
        # split the head-group axis across peers, concat received seq
        # blocks in source-rank order (= global sequence order)
        x = x.reshape(b, t, n, h // n * d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                               tiled=True)
        return x.reshape(b, n * t, h // n, d)

    def heads_to_seq(x):
        # inverse: [B, n*T, H/n, D] -> [B, T, H, D]. split the seq-block
        # axis across peers, concat received head groups in source-rank
        # order (= original head order)
        x = x.reshape(b, n, t, h // n * d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=3,
                               tiled=True)
        return x.reshape(b, t, h, d)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = dense_reference(qg, kg, vg)  # full-seq causal attn, H/n heads
    return heads_to_seq(out)


def ulysses_attention_sharded(q, k, v, mesh: Mesh,
                              data_axis: Optional[str], seq_axis: str,
                              model_axis: Optional[str]):
    """shard_map wrapper: q/k/v are global [B,S,H,D] arrays; B over
    data, S over seq, heads over model (same signature as
    ring_attention_sharded, so callers can switch schemes by name)."""
    n = mesh.shape[seq_axis]
    da = data_axis if data_axis in mesh.axis_names else None
    ma = model_axis if model_axis in mesh.axis_names else None
    # the guard must apply to the LOCAL head count: in_specs shard heads
    # over the model axis too, so each shard sees heads/model_size
    local_heads = q.shape[2] // (mesh.shape[ma] if ma else 1)
    if local_heads == 0 or local_heads % n != 0:
        raise ValueError(
            f"ulysses: local head count {local_heads} (= {q.shape[2]} "
            f"heads / model axis) not divisible by seq axis size {n}; "
            "use ring attention for this shape")
    spec = P(da, seq_axis, ma, None)

    fn = jax.shard_map(
        partial(ulysses_attention_local, axis_name=seq_axis),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
