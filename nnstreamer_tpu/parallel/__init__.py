"""Distributed execution: device meshes, sharding rules, ring attention,
and the sharded training step.

This package fills the reference's distributed slot (nnstreamer-edge TCP/
MQTT-hybrid fan-out, SURVEY.md §2.4) the TPU way: intra-pod scale is a
``jax.sharding.Mesh`` with XLA collectives over ICI; sequence parallelism
is first-class via ring attention (parallel/ring.py) and Ulysses-style
all-to-all head/sequence exchange (parallel/ulysses.py); cross-host streaming
stays in the query/edge elements (elements/query.py) over DCN sockets.
"""
from .mesh import best_mesh, make_mesh
from .sharding import GPT_RULES, named_sharding_tree, pspec_tree

__all__ = ["make_mesh", "best_mesh", "pspec_tree", "named_sharding_tree",
           "GPT_RULES"]
