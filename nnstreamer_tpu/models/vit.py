"""Vision Transformer — the dense-MXU vision model of the zoo.

MobileNet's depthwise convolutions under-use the systolic array by
construction (feature_group_count slices the MXU); a ViT is dense
matmuls end to end, so it is the model where MFU on TPU approaches the
hardware ceiling. Fills the classification slot the reference serves
with heavyweight backbones via its vendor SDK subplugins (ref:
ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc model
zoo usage in tests); here it is a first-class zoo citizen:

    zoo://vit?size=224&patch=16&d_model=768&layers=12&heads=12

Same output contract as mobilenet_v2 (uint8 frame in, [classes] float32
logits out) so image_labeling decodes it unchanged.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensors.info import TensorsInfo
from .zoo import jit_init, register_model


class EncoderBlock(nn.Module):
    d_model: int
    heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, dtype=self.dtype)(h, h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_model * self.mlp_ratio, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.dtype)(h)
        return x + h


class ViT(nn.Module):
    patch: int = 16
    d_model: int = 768
    layers: int = 12
    heads: int = 12
    classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # patch embedding: one conv with stride=kernel=patch (a dense
        # [p*p*3, d] matmul per patch on the MXU)
        x = nn.Conv(self.d_model, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype)(x)
        b, hp, wp, d = x.shape
        x = x.reshape(b, hp * wp, d)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, hp * wp, d), jnp.float32)
        x = x + pos.astype(self.dtype)
        for _ in range(self.layers):
            x = EncoderBlock(self.d_model, self.heads,
                             dtype=self.dtype)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = x.mean(axis=1)  # mean-pool (no cls token: shape-stable)
        return nn.Dense(self.classes, dtype=jnp.float32)(
            x.astype(jnp.float32))


@register_model("vit")
def _build_vit(size: str = "224", patch: str = "16", d_model: str = "768",
               layers: str = "12", heads: str = "12",
               classes: str = "1000", seed: str = "0"):
    hw = int(size)
    model = ViT(patch=int(patch), d_model=int(d_model), layers=int(layers),
                heads=int(heads), classes=int(classes))
    dummy = jnp.zeros((1, hw, hw, 3), jnp.bfloat16)
    params = jit_init(model, seed, dummy)

    def apply_fn(p, frame):
        batched = frame.ndim == 4
        x = frame.astype(jnp.bfloat16) / 127.5 - 1.0
        out = model.apply(p, x if batched else x[None])
        return out if batched else out[0]

    in_info = TensorsInfo.make("uint8", f"3:{hw}:{hw}")
    out_info = TensorsInfo.make("float32", classes)
    return apply_fn, params, in_info, out_info
