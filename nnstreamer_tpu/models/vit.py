"""Vision Transformer — the dense-MXU vision model of the zoo.

MobileNet's depthwise convolutions under-use the systolic array by
construction (feature_group_count slices the MXU); a ViT is dense
matmuls end to end, so it is the model where MFU on TPU approaches the
hardware ceiling. Fills the classification slot the reference serves
with heavyweight backbones via its vendor SDK subplugins (ref:
ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc model
zoo usage in tests); here it is a first-class zoo citizen:

    zoo://vit?size=224&patch=16&d_model=768&layers=12&heads=12

Same output contract as mobilenet_v2 (uint8 frame in, [classes] float32
logits out) so image_labeling decodes it unchanged.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensors.info import TensorsInfo
from .zoo import jit_init, register_model


class EncoderBlock(nn.Module):
    d_model: int
    heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    # fused=True routes the attention core through the Pallas kernel
    # (ops/attention.py): scores stay in VMEM instead of round-tripping
    # HBM as a [B,H,S,S] tensor. Same math, same params, same output —
    # a compile-time toggle, not a different model.
    fused: bool = False

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        attn_kwargs = {}
        if self.fused:
            from ..ops.attention import fused_attention
            attn_kwargs["attention_fn"] = fused_attention
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, dtype=self.dtype, **attn_kwargs)(h, h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_model * self.mlp_ratio, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.dtype)(h)
        return x + h


class ViT(nn.Module):
    patch: int = 16
    d_model: int = 768
    layers: int = 12
    heads: int = 12
    classes: int = 1000
    dtype: Any = jnp.bfloat16
    fused: bool = False

    @nn.compact
    def __call__(self, x):
        # patch embedding: one conv with stride=kernel=patch (a dense
        # [p*p*3, d] matmul per patch on the MXU)
        x = nn.Conv(self.d_model, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype)(x)
        b, hp, wp, d = x.shape
        x = x.reshape(b, hp * wp, d)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, hp * wp, d), jnp.float32)
        x = x + pos.astype(self.dtype)
        for _ in range(self.layers):
            x = EncoderBlock(self.d_model, self.heads,
                             dtype=self.dtype, fused=self.fused)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = x.mean(axis=1)  # mean-pool (no cls token: shape-stable)
        return nn.Dense(self.classes, dtype=jnp.float32)(
            x.astype(jnp.float32))


@register_model("vit")
def _build_vit(size: str = "224", patch: str = "16", d_model: str = "768",
               layers: str = "12", heads: str = "12",
               classes: str = "1000", seed: str = "0",
               attn: str = "auto"):
    """``attn``: ``stock`` (flax/XLA attention), ``pallas`` (the fused
    VMEM kernel, ops/attention.py). The param tree is identical either
    way — the toggle changes only how the attention core is scheduled.
    ``auto`` resolves to stock: measured on v5e, XLA's pattern-matched
    attention fusion beats the hand kernel at ViT encoder shapes
    (ops/attention.py docstring carries the numbers); pallas stays
    available for shapes where XLA's fusion breaks."""
    hw = int(size)
    if attn == "auto":
        attn = "stock"
    if attn not in ("stock", "pallas"):
        # a typo must not silently benchmark the wrong attention path
        raise ValueError(f"vit: attn must be auto|stock|pallas, "
                         f"got {attn!r}")
    model = ViT(patch=int(patch), d_model=int(d_model), layers=int(layers),
                heads=int(heads), classes=int(classes),
                fused=(attn == "pallas"))
    dummy = jnp.zeros((1, hw, hw, 3), jnp.bfloat16)
    params = jit_init(model, seed, dummy)

    def apply_fn(p, frame):
        batched = frame.ndim == 4
        x = frame.astype(jnp.bfloat16) / 127.5 - 1.0
        out = model.apply(p, x if batched else x[None])
        return out if batched else out[0]

    in_info = TensorsInfo.make("uint8", f"3:{hw}:{hw}")
    out_info = TensorsInfo.make("float32", classes)
    return apply_fn, params, in_info, out_info
