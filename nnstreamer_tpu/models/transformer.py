"""Decoder-only transformer LM — the flagship distributed/generative model.

Fills the slot of the reference's llama.cpp / llama2.c / executorch-llama
backends (ref: ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc —
async token streaming; _llama2.cc), but built TPU-first:

* plain-JAX param pytree with stable names so mesh partition rules are
  regex-over-path (see parallel/sharding.py) — Megatron-style tensor
  parallelism (column-split wq/wk/wv/w1/w3, row-split wo/w2);
* RoPE positions, RMSNorm, SwiGLU MLP, causal attention — all static
  shapes, scan-friendly;
* sequence parallelism via ring attention (parallel/ring.py) when a
  ``seq`` mesh axis is present;
* KV-cache single-token decode step for the generative filter path.

Zoo entries: ``zoo://gpt?...`` (logits fn) used by tests/bench; the
generative pipeline uses filters/llm.py on top of this module.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..tensors.info import TensorsInfo
from .zoo import register_model


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 0          # 0 -> 4*d_model
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # distributed knobs (None = single chip)
    mesh: Optional[jax.sharding.Mesh] = None
    data_axis: Optional[str] = "data"
    seq_axis: Optional[str] = None     # set to e.g. "seq" for seq parallelism
    model_axis: Optional[str] = "model"
    # sequence-parallel attention scheme: "ring" (K/V ppermute ring,
    # any head count) or "ulysses" (all-to-all head/seq exchange, needs
    # heads % seq_axis_size == 0; fewer collectives) — both exact
    seq_scheme: str = "ring"

    @property
    def ff(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: GPTConfig, key: jax.Array) -> Dict[str, Any]:
    """Param tree with path names the partition rules key off.

    Jitted on ``cfg`` (frozen, hashable): the whole tree materializes in
    ONE compiled dispatch instead of 9x n_layers eager ops — on a
    tunneled dev chip each eager op is a full RPC round trip."""
    return _init_params_jit(cfg, key)


@partial(jax.jit, static_argnums=0)
def _init_params_jit(cfg: GPTConfig, key: jax.Array) -> Dict[str, Any]:
    dt = cfg.dtype
    d, f, v = cfg.d_model, cfg.ff, cfg.vocab

    def dense(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    keys = jax.random.split(key, 2 + cfg.n_layers)
    params: Dict[str, Any] = {
        "embed": dense(keys[0], (v, d), d ** -0.5),
        "head": dense(keys[1], (d, v), d ** -0.5),
        "ln_f": jnp.ones((d,), dt),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        ks = jax.random.split(keys[2 + i], 7)
        params["layers"].append({
            "ln1": jnp.ones((d,), dt),
            "wq": dense(ks[0], (d, d), d ** -0.5),
            "wk": dense(ks[1], (d, d), d ** -0.5),
            "wv": dense(ks[2], (d, d), d ** -0.5),
            "wo": dense(ks[3], (d, d), (2 * d * cfg.n_layers) ** -0.5),
            "ln2": jnp.ones((d,), dt),
            "w1": dense(ks[4], (d, f), d ** -0.5),
            "w3": dense(ks[5], (d, f), d ** -0.5),
            "w2": dense(ks[6], (f, d), (2 * f * cfg.n_layers) ** -0.5),
        })
    return params


def rmsnorm(x, scale, eps: float = 1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def rope(x, positions, theta: float):
    """Rotary embedding over the last dim. x: [..., S, H, Dh]."""
    dh = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dh // 2, dtype=jnp.float32) / (dh // 2))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., S, Dh/2]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _constrain(x, cfg: GPTConfig, spec: Tuple):
    """Activation sharding hint; no-op off-mesh."""
    if cfg.mesh is None:
        return x
    axes = tuple(a if (a is None or a in cfg.mesh.axis_names) else None
                 for a in spec)
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(cfg.mesh, jax.sharding.PartitionSpec(*axes)))


def _dense_attention(q, k, v, positions_q, positions_k):
    """q,k,v: [B,S,H,Dh]; causal by absolute position."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = positions_q[:, None, :, None] >= positions_k[:, None, None, :]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _attention(q, k, v, positions, cfg: GPTConfig):
    if cfg.seq_scheme not in ("ring", "ulysses"):
        # both schemes are exact, so a typo would be undetectable from
        # outputs — fail loudly instead of silently running ring
        raise ValueError(f"unknown seq_scheme {cfg.seq_scheme!r}; "
                         "expected 'ring' or 'ulysses'")
    if cfg.mesh is not None and cfg.seq_axis in cfg.mesh.axis_names \
            and cfg.mesh.shape[cfg.seq_axis] > 1:
        if cfg.seq_scheme == "ulysses":
            from ..parallel.ulysses import ulysses_attention_sharded
            return ulysses_attention_sharded(
                q, k, v, cfg.mesh, cfg.data_axis, cfg.seq_axis,
                cfg.model_axis)
        from ..parallel.ring import ring_attention_sharded
        return ring_attention_sharded(q, k, v, cfg.mesh, cfg.data_axis,
                                      cfg.seq_axis, cfg.model_axis)
    return _dense_attention(q, k, v, positions, positions)


def block(h, layer, positions, cfg: GPTConfig, return_kv: bool = False):
    """One transformer block; with ``return_kv`` also hands back the
    roped K and raw V so prefill can seed a decode cache from the SAME
    computation (no duplicated block body)."""
    b, s, d = h.shape
    hd, nh = cfg.head_dim, cfg.n_heads
    x = rmsnorm(h, layer["ln1"])
    q = (x @ layer["wq"]).reshape(b, s, nh, hd)
    k = (x @ layer["wk"]).reshape(b, s, nh, hd)
    v = (x @ layer["wv"]).reshape(b, s, nh, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    attn = _attention(q, k, v, positions, cfg)
    h = h + attn.reshape(b, s, d) @ layer["wo"]
    h = _constrain(h, cfg, (cfg.data_axis, cfg.seq_axis, None))
    x = rmsnorm(h, layer["ln2"])
    ff = jax.nn.silu(x @ layer["w1"]) * (x @ layer["w3"])
    ff = _constrain(ff, cfg, (cfg.data_axis, cfg.seq_axis, cfg.model_axis))
    h = h + ff @ layer["w2"]
    h = _constrain(h, cfg, (cfg.data_axis, cfg.seq_axis, None))
    return (h, k, v) if return_kv else h


def forward(params, tokens, cfg: GPTConfig):
    """tokens [B,S] int32 -> logits [B,S,V] float32."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    h = jnp.take(params["embed"], tokens, axis=0)
    h = _constrain(h, cfg, (cfg.data_axis, cfg.seq_axis, None))
    for layer in params["layers"]:
        h = block(h, layer, positions, cfg)
    h = rmsnorm(h, params["ln_f"])
    logits = (h @ params["head"]).astype(jnp.float32)
    return _constrain(logits, cfg, (cfg.data_axis, cfg.seq_axis, cfg.model_axis))


def loss_fn(params, batch, cfg: GPTConfig):
    """Next-token cross-entropy; batch = tokens [B,S+1] int32."""
    inputs, targets = batch[:, :-1], batch[:, 1:]
    logits = forward(params, inputs, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# -- KV-cache decode (generative path) ------------------------------------

def init_cache(cfg: GPTConfig, batch: int, max_len: int) -> Dict[str, Any]:
    shape = (cfg.n_layers, batch, max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype),
            "index": jnp.zeros((), jnp.int32)}


def prefill(params, cache, tokens, cfg: GPTConfig, true_len=None):
    """Whole-prompt prefill in ONE dispatch: tokens [B,T] int32 ->
    (logits [B,V] for the last real position, cache with K/V written at
    positions 0..T-1 and index=true_len).

    ≙ llamacpp's n_batch prompt ingestion
    (tensor_filter_llamacpp.cc:267) — the causal forward runs batched on
    the MXU instead of T sequential single-token dispatches; the decode
    loop then continues from the returned cache. Built on the same
    block() as forward(), so mesh sharding constraints and ring
    attention apply to prefill too.

    ``true_len`` (a traced int32 scalar <= T) supports length-bucketed
    padding: callers pad prompts to a few fixed shapes so jit compiles
    O(log max_len) variants instead of one per prompt length. Padded
    positions are causal-masked garbage that is never read: logits come
    from position true_len-1, and the decode loop overwrites padded
    cache slots (at positions >= true_len) before its validity mask
    (arange <= pos) can reach them.
    """
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    h = jnp.take(params["embed"], tokens, axis=0)
    h = _constrain(h, cfg, (cfg.data_axis, cfg.seq_axis, None))
    new_k, new_v = [], []
    for i, layer in enumerate(params["layers"]):
        h, k, v = block(h, layer, positions, cfg, return_kv=True)
        new_k.append(jax.lax.dynamic_update_slice(
            cache["k"][i], k.astype(cache["k"].dtype), (0, 0, 0, 0)))
        new_v.append(jax.lax.dynamic_update_slice(
            cache["v"][i], v.astype(cache["v"].dtype), (0, 0, 0, 0)))
    h = rmsnorm(h, params["ln_f"])
    t_eff = jnp.asarray(t if true_len is None else true_len, jnp.int32)
    # dynamic index on the seq axis; clamps (never wraps) when out of
    # range, so a zero-length prompt cannot read the padded tail
    h_last = jax.lax.dynamic_slice_in_dim(h, t_eff - 1, 1, axis=1)[:, 0]
    logits = (h_last @ params["head"]).astype(jnp.float32)
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v),
             "index": t_eff}
    return logits, cache


def decode_step(params, cache, token, cfg: GPTConfig):
    """One-token decode: token [B] int32 -> (logits [B,V], new cache).

    The cache is functional state threaded by the caller — the XLA-friendly
    shape of llamacpp's internal context (static shapes, dynamic_update_slice).
    A thin shim over :func:`decode_step_multi` (shared scalar index
    broadcast to per-row positions) so the single- and multi-stream paths
    cannot drift."""
    b = token.shape[0]
    mcache = {"k": cache["k"], "v": cache["v"],
              "index": jnp.broadcast_to(cache["index"], (b,))}
    logits, mcache = decode_step_multi(
        params, mcache, token, jnp.ones((b,), bool), cfg)
    return logits, {"k": mcache["k"], "v": mcache["v"],
                    "index": mcache["index"][0]}


def init_cache_multi(cfg: GPTConfig, batch: int, max_len: int) -> Dict[str, Any]:
    """Continuous-batching cache: per-slot positions (index [B]) so B
    independent streams at different depths share one decode dispatch."""
    cache = init_cache(cfg, batch, max_len)
    cache["index"] = jnp.zeros((batch,), jnp.int32)
    return cache


def cache_insert(bcache, cache1, slot):
    """Insert a batch-1 prefill cache into slot ``slot`` of a
    multi-stream cache (same max_len). The whole K/V slice is replaced,
    so stale tokens from the slot's previous occupant cannot leak."""
    k = jax.lax.dynamic_update_slice(
        bcache["k"], cache1["k"].astype(bcache["k"].dtype), (0, slot, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(
        bcache["v"], cache1["v"].astype(bcache["v"].dtype), (0, slot, 0, 0, 0))
    idx = jax.lax.dynamic_update_slice(
        bcache["index"], cache1["index"].reshape(1).astype(jnp.int32), (slot,))
    return {"k": k, "v": v, "index": idx}


def decode_step_multi(params, cache, token, active, cfg: GPTConfig):
    """One decode step for B *independent* streams in ONE dispatch
    (continuous-batching lite — the TPU-first answer to llamacpp's
    n_batch, tensor_filter_llamacpp.cc:267). token [B] int32,
    active [B] bool; cache index is per-slot [B]. Inactive slots do not
    advance their index; their lanes compute garbage that the scheduler
    never emits. Lanes whose position has reached max_len likewise
    neither write nor advance: dynamic_update_slice would clamp such a
    write onto row max_len-1, corrupting the last real cache row — the
    in-graph form of the single-stream loop's "never decode past
    capacity" guard (the emitted token stream is unchanged: logits a
    full lane produces past capacity are never sampled)."""
    b = token.shape[0]
    pos = cache["index"]                       # [B]
    positions = pos[:, None]                   # [B,1]
    h = jnp.take(params["embed"], token[:, None], axis=0)
    max_len = cache["k"].shape[2]
    valid = jnp.arange(max_len)[None, :] <= pos[:, None]   # [B,L]
    ok = active & (pos < max_len)              # may write + advance
    lane = ok[:, None, None, None]             # [B,1,1,1] over [B,1,nh,hd]
    # per-slot cache write: each row lands at its own position. Guarded
    # lanes write their OLD row back (a no-op) instead of their new k/v:
    # masking the one-row update is free, where a whole-cache select
    # per layer would double the decode step's HBM traffic
    upd = jax.vmap(
        lambda c, x, p: jax.lax.dynamic_update_slice(c, x, (p, 0, 0)))
    row = jax.vmap(
        lambda c, p: jax.lax.dynamic_slice(
            c, (p, 0, 0), (1, c.shape[1], c.shape[2])))
    new_k, new_v = [], []
    for i, layer in enumerate(params["layers"]):
        hd, nh = cfg.head_dim, cfg.n_heads
        x = rmsnorm(h, layer["ln1"])
        q = rope((x @ layer["wq"]).reshape(b, 1, nh, hd), positions,
                 cfg.rope_theta)
        k1 = rope((x @ layer["wk"]).reshape(b, 1, nh, hd), positions,
                  cfg.rope_theta)
        v1 = (x @ layer["wv"]).reshape(b, 1, nh, hd)
        k = upd(cache["k"][i],
                jnp.where(lane, k1.astype(cache["k"].dtype),
                          row(cache["k"][i], pos)), pos)
        v = upd(cache["v"][i],
                jnp.where(lane, v1.astype(cache["v"].dtype),
                          row(cache["v"][i], pos)), pos)
        new_k.append(k)
        new_v.append(v)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores * (hd ** -0.5)
        scores = jnp.where(valid[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        h = h + attn.reshape(b, 1, -1) @ layer["wo"]
        x = rmsnorm(h, layer["ln2"])
        ff = jax.nn.silu(x @ layer["w1"]) * (x @ layer["w3"])
        h = h + ff @ layer["w2"]
    h = rmsnorm(h, params["ln_f"])
    logits = (h[:, 0] @ params["head"]).astype(jnp.float32)
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v),
             "index": pos + ok.astype(jnp.int32)}
    return logits, cache


def sample_logits(keys, logits, temperature: float, top_k: int = 0,
                  top_p: float = 1.0):
    """Per-stream token sampling, jit-safe, shared by the host decode
    loops and the scanned chunk body so every path draws identical
    tokens for the same keys.

    keys [B,2] uint32, logits [B,V] f32 -> [B] int32. temperature<=0 is
    greedy argmax (keys ignored). top_k keeps the K best logits, top_p
    the smallest prefix of the sorted distribution with cumulative
    probability >= p (nucleus sampling) — the knobs llamacpp exposes on
    the reference's generative slot (tensor_filter_llamacpp.cc sampler
    chain), computed in-graph on device.
    """
    if temperature <= 0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    # llamacpp chain order: the top_k/top_p nucleus is formed on the
    # UNSCALED distribution, temperature only shapes the final draw —
    # so migrated configs keep their candidate sets
    l0 = logits.astype(jnp.float32)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(l0, min(top_k, l0.shape[-1]))[0][..., -1:]
        l0 = jnp.where(l0 < kth, -jnp.inf, l0)
    if top_p < 1.0:
        srt = jnp.flip(jnp.sort(l0, axis=-1), axis=-1)
        probs = jax.nn.softmax(srt, axis=-1)
        exclusive = jnp.cumsum(probs, axis=-1) - probs
        # exclusive <= 0 always keeps the best token: top_p<=0 must
        # degrade to greedy, not to an all-masked row (categorical over
        # all -inf silently returns index 0)
        kept = jnp.where((exclusive < top_p) | (exclusive <= 0.0),
                         srt, jnp.inf)
        thr = jnp.min(kept, axis=-1, keepdims=True)  # smallest kept logit
        l0 = jnp.where(l0 < thr, -jnp.inf, l0)
    return jax.vmap(lambda k, row: jax.random.categorical(k, row))(
        keys, l0 / temperature).astype(jnp.int32)


def decode_chunk_multi(params, cache, logits, keys, active, cfg: GPTConfig,
                       *, steps: int, temperature: float = 0.0,
                       top_k: int = 0, top_p: float = 1.0):
    """``steps`` sample+decode rounds for B streams in ONE dispatch.

    A ``lax.scan`` over :func:`decode_step_multi` with the sampling
    (greedy argmax, or categorical at ``temperature``) folded into the
    graph, so token generation costs 1/steps of the dispatches — and,
    crucially for a remote-attached chip, 1/steps of the host round
    trips: the caller fetches a [steps, B] token block instead of B ids
    per step. The per-stream key-split order matches the host-side
    sampling loop exactly, so chunked and unchunked generation emit
    identical tokens for the same seed.

    The reference's llamacpp slot has no analog (its decode loop is
    host-driven per token); this is the XLA-native shape of generation:
    static chunk length, in-graph control flow (SURVEY.md §7 stance).

    Args: logits [B,V] from prefill or the previous chunk; keys [B,2]
    uint32 PRNG keys (ignored when temperature==0); active [B] bool.
    Returns (tokens [steps, B] int32, logits, cache, keys).
    """
    def body(carry, _):
        lg, ca, ks = carry
        if temperature > 0:
            pair = jax.vmap(jax.random.split)(ks)      # [B,2,2]
            ks2, subs = pair[:, 0], pair[:, 1]
            tok = sample_logits(subs, lg, temperature, top_k, top_p)
        else:
            ks2 = ks
            tok = sample_logits(ks, lg, 0.0)
        lg2, ca2 = decode_step_multi(params, ca, tok, active, cfg)
        return (lg2, ca2, ks2), tok

    (logits, cache, keys), toks = jax.lax.scan(
        body, (logits, cache, keys), None, length=steps)
    return toks, logits, cache, keys


# -- paged KV pool (block-granular cache, vLLM-style) ---------------------
#
# The contiguous multi-stream cache above reserves a worst-case
# [max_len] lane per slot, so decode occupancy is stream-counted. The
# pool below is the token-budgeted alternative: a shared arena of
# fixed-size blocks ([L, NB, bs, H, Dh]) addressed through per-stream
# block tables, with allocation/refcounts/prefix-sharing managed
# host-side (filters/kvpool.py). decode_step_paged gathers a stream's
# blocks into the SAME [B, max_len] layout decode_step_multi attends
# over and runs the identical op sequence on it, so the paged path is
# bit-exact against the contiguous path on CPU — the parity gate
# tests/test_llm_disagg.py enforces.

def init_kv_pool(cfg: GPTConfig, n_blocks: int, block_size: int) -> Dict[str, Any]:
    """Block arena: {"k","v"} [L, NB, bs, H, Dh]. Block 0 is an
    ordinary block; the host allocator decides which phys ids are live.
    Index NB (one past the end) is the discard target for guarded
    scatter writes (mode="drop")."""
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def pool_insert(pool, kb, vb, phys):
    """Write whole blocks: kb/vb [L, nb, bs, H, Dh] into phys [nb].
    Entire blocks are replaced, so a reused block cannot leak its
    previous occupant's rows into the freshly inserted span."""
    return {"k": pool["k"].at[:, phys].set(kb.astype(pool["k"].dtype),
                                           mode="drop"),
            "v": pool["v"].at[:, phys].set(vb.astype(pool["v"].dtype),
                                           mode="drop")}


def pool_copy_block(pool, src, dst):
    """Copy-on-write helper: duplicate block ``src`` into ``dst`` so a
    writer can diverge from a shared prefix block without touching the
    readers' copy."""
    return {"k": pool["k"].at[:, dst].set(pool["k"][:, src]),
            "v": pool["v"].at[:, dst].set(pool["v"][:, src])}


def pool_gather(pool, phys):
    """Gather blocks phys [nb] -> contiguous (k, v) [L, nb*bs, H, Dh]
    (the shipped-KV / prefill-with-past layout)."""
    k = pool["k"][:, phys]
    v = pool["v"][:, phys]
    flat = (k.shape[0], k.shape[1] * k.shape[2], k.shape[3], k.shape[4])
    return k.reshape(flat), v.reshape(flat)


def decode_step_paged(params, pool, table, index, token, active,
                      cfg: GPTConfig, *, max_len: int):
    """One decode step for B streams whose KV lives in pool blocks.

    table [B, W] int32 maps each stream's block index to a phys block;
    index [B] is the per-stream position. Each layer gathers the
    stream's blocks into a contiguous [B, max_len] view and then runs
    decode_step_multi's exact op sequence on it (same one-row masked
    update, same einsums, same [B, max_len] mask shape), so logits are
    bit-identical to the contiguous path — gathered bytes equal lane
    bytes, and the trailing W*bs - max_len garbage columns are sliced
    off before the softmax ever sees them. The new row is persisted
    into the pool by a separate guarded scatter: inactive / at-capacity
    lanes aim at phys id NB (one past the arena) and mode="drop"
    discards the write, the scatter-shaped form of decode_step_multi's
    "guarded lanes rewrite their old row" trick.

    Returns (logits [B,V], pool', index'). Shared prefix blocks are
    never written: the host allocator caps prefix adoption below the
    first decode-written block, so every scatter target is
    stream-private by construction."""
    b = token.shape[0]
    nb, bs_blk = pool["k"].shape[1], pool["k"].shape[2]
    hd, nh = cfg.head_dim, cfg.n_heads
    pos = index                                # [B]
    positions = pos[:, None]
    h = jnp.take(params["embed"], token[:, None], axis=0)
    valid = jnp.arange(max_len)[None, :] <= pos[:, None]
    ok = active & (pos < max_len)
    lane = ok[:, None, None, None]
    upd = jax.vmap(
        lambda c, x, p: jax.lax.dynamic_update_slice(c, x, (p, 0, 0)))
    row = jax.vmap(
        lambda c, p: jax.lax.dynamic_slice(
            c, (p, 0, 0), (1, c.shape[1], c.shape[2])))
    blk = jnp.clip(pos // bs_blk, 0, table.shape[1] - 1)
    phys = jnp.take_along_axis(table, blk[:, None], axis=1)[:, 0]
    tgt = jnp.where(ok, phys, nb)              # nb = discard target
    off = pos % bs_blk
    k_rows, v_rows = [], []
    for i, layer in enumerate(params["layers"]):
        kc = pool["k"][i][table].reshape(b, -1, nh, hd)[:, :max_len]
        vc = pool["v"][i][table].reshape(b, -1, nh, hd)[:, :max_len]
        x = rmsnorm(h, layer["ln1"])
        q = rope((x @ layer["wq"]).reshape(b, 1, nh, hd), positions,
                 cfg.rope_theta)
        k1 = rope((x @ layer["wk"]).reshape(b, 1, nh, hd), positions,
                  cfg.rope_theta)
        v1 = (x @ layer["wv"]).reshape(b, 1, nh, hd)
        kd = jnp.where(lane, k1.astype(kc.dtype), row(kc, pos))
        vd = jnp.where(lane, v1.astype(vc.dtype), row(vc, pos))
        k = upd(kc, kd, pos)
        v = upd(vc, vd, pos)
        k_rows.append(kd[:, 0])
        v_rows.append(vd[:, 0])
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores * (hd ** -0.5)
        scores = jnp.where(valid[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        h = h + attn.reshape(b, 1, -1) @ layer["wo"]
        x = rmsnorm(h, layer["ln2"])
        ff = jax.nn.silu(x @ layer["w1"]) * (x @ layer["w3"])
        h = h + ff @ layer["w2"]
    h = rmsnorm(h, params["ln_f"])
    logits = (h[:, 0] @ params["head"]).astype(jnp.float32)
    pool = {"k": pool["k"].at[:, tgt, off].set(jnp.stack(k_rows),
                                               mode="drop"),
            "v": pool["v"].at[:, tgt, off].set(jnp.stack(v_rows),
                                               mode="drop")}
    return logits, pool, pos + ok.astype(jnp.int32)


def decode_chunk_paged(params, pool, table, index, logits, keys, active,
                       cfg: GPTConfig, *, steps: int, max_len: int,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0):
    """``steps`` sample+decode rounds over the paged cache in ONE
    dispatch — decode_chunk_multi's scan body with decode_step_paged
    substituted. The block table is a scan constant: the scheduler
    admits new streams only between chunks, and each stream's blocks
    are preallocated through its emit budget, so no table edit can be
    needed mid-chunk. The per-stream key-split order matches
    decode_chunk_multi exactly, so paged chunked generation emits the
    same tokens as every other path for the same seed.

    Returns (tokens [steps, B] int32, logits, pool, index, keys)."""
    def body(carry, _):
        lg, pl, idx, ks = carry
        if temperature > 0:
            pair = jax.vmap(jax.random.split)(ks)
            ks2, subs = pair[:, 0], pair[:, 1]
            tok = sample_logits(subs, lg, temperature, top_k, top_p)
        else:
            ks2 = ks
            tok = sample_logits(ks, lg, 0.0)
        lg2, pl2, idx2 = decode_step_paged(
            params, pl, table, idx, tok, active, cfg, max_len=max_len)
        return (lg2, pl2, idx2, ks2), tok

    (logits, pool, index, keys), toks = jax.lax.scan(
        body, (logits, pool, index, keys), None, length=steps)
    return toks, logits, pool, index, keys


def prefill_with_past(params, past_k, past_v, past_len, tokens,
                      cfg: GPTConfig, true_len=None):
    """Suffix prefill over an existing KV prefix: run the prompt TAIL
    (tokens [1, S], ``true_len`` real) with attention over
    concat(past, suffix), where past_k/past_v [L, P, H, Dh] hold
    ``past_len`` valid rows (the rest padded garbage, column-masked).

    This is the other half of the prefix cache and of the wire KV
    handoff: a prompt whose first ``past_len`` tokens hit warm blocks
    (or arrived from a prefill replica) only pays compute for the
    suffix. RoPE positions are offset by ``past_len`` (traced, so one
    compiled variant serves every split point of a (P, S) bucket pair)
    and causality is by absolute position, exactly as in block().

    Returns (logits [1, V] at suffix position true_len-1,
    suffix K [L, S, H, Dh], suffix V) — the caller block-aligns and
    inserts the suffix KV into the pool."""
    b, s = tokens.shape
    p = past_k.shape[1]
    hd, nh = cfg.head_dim, cfg.n_heads
    p0 = jnp.asarray(past_len, jnp.int32)
    pos_q = p0 + jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    past_cols = jnp.arange(p, dtype=jnp.int32)
    # padded past rows sit at absolute positions < pos_q, so the causal
    # mask alone would admit them — the column-validity mask is load-bearing
    col_ok = jnp.concatenate([past_cols < p0, jnp.ones((s,), bool)])
    h = jnp.take(params["embed"], tokens, axis=0)
    new_k, new_v = [], []
    for i, layer in enumerate(params["layers"]):
        x = rmsnorm(h, layer["ln1"])
        q = rope((x @ layer["wq"]).reshape(b, s, nh, hd), pos_q,
                 cfg.rope_theta)
        k = rope((x @ layer["wk"]).reshape(b, s, nh, hd), pos_q,
                 cfg.rope_theta)
        v = (x @ layer["wv"]).reshape(b, s, nh, hd)
        fk = jnp.concatenate(
            [jnp.broadcast_to(past_k[i][None].astype(k.dtype),
                              (b, p, nh, hd)), k], axis=1)
        fv = jnp.concatenate(
            [jnp.broadcast_to(past_v[i][None].astype(v.dtype),
                              (b, p, nh, hd)), v], axis=1)
        pos_k = jnp.concatenate(
            [jnp.broadcast_to(past_cols, (b, p)), pos_q], axis=1)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, fk).astype(jnp.float32)
        scores = scores * (hd ** -0.5)
        mask = (pos_q[:, None, :, None] >= pos_k[:, None, None, :]) \
            & col_ok[None, None, None, :]
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, fv)
        h = h + attn.reshape(b, s, -1) @ layer["wo"]
        x = rmsnorm(h, layer["ln2"])
        ff = jax.nn.silu(x @ layer["w1"]) * (x @ layer["w3"])
        h = h + ff @ layer["w2"]
        new_k.append(k)
        new_v.append(v)
    h = rmsnorm(h, params["ln_f"])
    t_eff = jnp.asarray(s if true_len is None else true_len, jnp.int32)
    h_last = jax.lax.dynamic_slice_in_dim(h, t_eff - 1, 1, axis=1)[:, 0]
    logits = (h_last @ params["head"]).astype(jnp.float32)
    # single-stream path (b == 1): drop the batch dim so the suffix KV
    # has the same [L, S, H, Dh] layout as shipped / gathered KV
    return logits, jnp.stack(new_k)[:, 0], jnp.stack(new_v)[:, 0]


@register_model("gpt")
def _build_gpt(vocab: str = "32000", d_model: str = "512", n_heads: str = "8",
               n_layers: str = "6", seq: str = "128", seed: str = "0"):
    """Logit-model zoo entry: int32 token frame [S] -> float32 logits [S,V]."""
    cfg = GPTConfig(vocab=int(vocab), d_model=int(d_model),
                    n_heads=int(n_heads), n_layers=int(n_layers))
    params = init_params(cfg, jax.random.PRNGKey(int(seed)))
    s = int(seq)

    def apply_fn(p, tokens):
        return forward(p, tokens[None].astype(jnp.int32), cfg)[0]

    in_info = TensorsInfo.make("int32", str(s))
    out_info = TensorsInfo.make("float32", f"{cfg.vocab}:{s}")
    return apply_fn, params, in_info, out_info
