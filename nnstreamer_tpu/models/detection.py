"""Detection / pose / segmentation zoo models (flax, MXU-first).

The BASELINE configs 2-4 (SSD-MobileNet-v2 bounding boxes, PoseNet
multi-output, DeepLab-v3 segmentation — BASELINE.md table) need native
models wired to the existing decoders:

- ``zoo://ssd_mobilenet_v2``   -> bounding_boxes mode=mobilenet-ssd-postprocess
  (emits the TFLite detection-postprocess tensor quad: boxes [N,4]
  ymin:xmin:ymax:xmax normalized, classes [N], scores [N], count [1] —
  ≙ ext/nnstreamer/tensor_decoder/box_properties/mobilenetssdpp.cc)
- ``zoo://posenet``            -> pose_estimation (heatmaps [H',W',K]
  ≙ tensordec-pose.c heatmap mode)
- ``zoo://deeplab_v3``         -> image_segment (logits [H,W,21]
  ≙ tensordec-imagesegment.c tflite-deeplab mode)

All share the MobileNetV2 backbone (models/mobilenet.py), run conv math
in bfloat16 on the MXU, and keep their postprocessing INSIDE the jitted
graph (top-k on device, resize on device) so one invoke = one XLA
program. Random init by default; ``params_dir=`` loads trained weights.
"""
from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensors.info import TensorsInfo
from .mobilenet import ConvBN, MobileNetV2, _V2_BLOCKS, _make_divisible
from .zoo import jit_init, register_model


class _Backbone(nn.Module):
    """MobileNetV2 feature extractor up to a chosen stride (8/16/32)."""

    width: float = 1.0
    max_stride: int = 16
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        stride = 2
        x = ConvBN(_make_divisible(32 * self.width), kernel=(3, 3),
                   strides=(2, 2), dtype=self.dtype)(x)
        from .mobilenet import InvertedResidual
        for t, c, n, s in _V2_BLOCKS:
            ch = _make_divisible(c * self.width)
            for i in range(n):
                blk_s = s if i == 0 else 1
                if stride * blk_s > self.max_stride:
                    blk_s = 1  # atrous-style: keep resolution
                stride *= blk_s if i == 0 and s > 1 and \
                    stride * s <= self.max_stride else 1
                x = InvertedResidual(ch, (blk_s, blk_s), t,
                                     dtype=self.dtype)(x)
        return x


class SSDHead(nn.Module):
    """Single-scale dense detection head (anchor-free center style):
    per-cell class scores + box offsets, postprocessed to the ssd-pp
    tensor quad in-graph."""

    num_classes: int = 91
    topk: int = 100
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feat):
        h, w, _ = feat.shape[-3:]
        cls = nn.Conv(self.num_classes, (3, 3), padding="SAME",
                      dtype=self.dtype)(feat)
        box = nn.Conv(4, (3, 3), padding="SAME", dtype=self.dtype)(feat)
        scores = jax.nn.sigmoid(cls.astype(jnp.float32)).reshape(
            -1, self.num_classes)
        deltas = jnp.tanh(box.astype(jnp.float32)).reshape(-1, 4)
        # anchor grid: one center anchor per cell
        ys, xs = jnp.meshgrid(
            (jnp.arange(h) + 0.5) / h, (jnp.arange(w) + 0.5) / w,
            indexing="ij")
        cy = ys.reshape(-1) + deltas[:, 0] * 0.5
        cx = xs.reshape(-1) + deltas[:, 1] * 0.5
        bh = jnp.exp(deltas[:, 2]) * (2.0 / h)
        bw = jnp.exp(deltas[:, 3]) * (2.0 / w)
        best = jnp.max(scores, axis=1)
        cls_id = jnp.argmax(scores, axis=1)
        top_scores, idx = jax.lax.top_k(best, self.topk)
        boxes = jnp.stack([
            jnp.clip(cy[idx] - bh[idx] / 2, 0, 1),
            jnp.clip(cx[idx] - bw[idx] / 2, 0, 1),
            jnp.clip(cy[idx] + bh[idx] / 2, 0, 1),
            jnp.clip(cx[idx] + bw[idx] / 2, 0, 1)], axis=1)
        return (boxes, cls_id[idx].astype(jnp.float32), top_scores,
                jnp.asarray([float(self.topk)], jnp.float32))


class SSDMobileNetV2(nn.Module):
    num_classes: int = 91
    width: float = 1.0
    topk: int = 100

    @nn.compact
    def __call__(self, x):
        feat = _Backbone(width=self.width, max_stride=16)(x)
        return SSDHead(num_classes=self.num_classes, topk=self.topk)(feat)


@register_model("ssd_mobilenet_v2")
def _build_ssd(width: str = "1.0", num_classes: str = "91",
               size: str = "300", topk: str = "100", seed: str = "0",
               packed: str = "0"):
    """``packed=1`` concatenates the ssd-pp quad into ONE flat float32
    tensor [6K+1] inside the jitted graph (free on device), so a host
    consumer pays a single D2H instead of four — on a tunneled chip each
    synchronous D2H costs ~10 ms of latency. The bounding_boxes decoder
    unpacks the layout transparently."""
    w, nc, hw, k = float(width), int(num_classes), int(size), int(topk)
    want_packed = packed not in ("0", "", "false")
    model = SSDMobileNetV2(num_classes=nc, width=w, topk=k)
    dummy = jnp.zeros((1, hw, hw, 3), jnp.bfloat16)
    params = jit_init(model, seed, dummy)

    def apply_one(p, frame):
        x = frame.astype(jnp.bfloat16) / 127.5 - 1.0
        boxes, classes, scores, count = model.apply(p, x[None])
        if want_packed:
            return jnp.concatenate([boxes.reshape(-1), classes,
                                    scores, count])
        return boxes, classes, scores, count

    def apply_fn(p, frame):
        if frame.ndim == 4:  # batched invoke: vmap the per-frame path
            return jax.vmap(lambda f: apply_one(p, f))(frame)
        return apply_one(p, frame)

    in_info = TensorsInfo.make("uint8", f"3:{hw}:{hw}")
    out_info = TensorsInfo.make("float32", str(6 * k + 1)) if want_packed \
        else TensorsInfo.make(
            "float32,float32,float32,float32", f"4:{k},{k},{k},1")
    return apply_fn, params, in_info, out_info


class PoseNet(nn.Module):
    """Heatmap pose head over the /16 backbone (17 COCO keypoints)."""

    keypoints: int = 17
    width: float = 1.0

    @nn.compact
    def __call__(self, x):
        feat = _Backbone(width=self.width, max_stride=16)(x)
        hm = nn.Conv(self.keypoints, (1, 1), dtype=jnp.bfloat16)(feat)
        return jax.nn.sigmoid(hm.astype(jnp.float32))


@register_model("posenet")
def _build_posenet(width: str = "1.0", size: str = "257",
                   keypoints: str = "17", seed: str = "0",
                   decode: str = "0"):
    """``decode=device`` folds per-keypoint argmax into the XLA program
    and emits [K, 3] (x, y, score; normalized, pose-decoder "key" form)
    instead of the [H', W', K] heatmap — ~100x less D2H traffic and no
    host-side argmax. The decoder's heatmap mode stays the parity path
    (≙ tensordec-pose.c consumes raw heatmaps); this is the TPU-first
    option, like deeplab's argmax=u8."""
    w, hw, kp = float(width), int(size), int(keypoints)
    want_decode = decode not in ("0", "", "false")
    model = PoseNet(keypoints=kp, width=w)
    dummy = jnp.zeros((1, hw, hw, 3), jnp.bfloat16)
    params = jit_init(model, seed, dummy)

    def keypoints_of(hm):
        hp, wp, k = hm.shape
        flat = hm.reshape(-1, k)
        idx = jnp.argmax(flat, axis=0)
        ys = (idx // wp).astype(jnp.float32) / max(hp - 1, 1)
        xs = (idx % wp).astype(jnp.float32) / max(wp - 1, 1)
        scores = jnp.take_along_axis(flat, idx[None], axis=0)[0]
        return jnp.stack([xs, ys, scores], axis=1)  # [K, 3]

    def apply_fn(p, frame):
        batched = frame.ndim == 4
        x = frame.astype(jnp.bfloat16) / 127.5 - 1.0
        out = model.apply(p, x if batched else x[None])
        if want_decode:
            out = jax.vmap(keypoints_of)(out)
        return out if batched else out[0]

    hm = hw // 16 + (1 if hw % 16 else 0)
    in_info = TensorsInfo.make("uint8", f"3:{hw}:{hw}")
    out_info = TensorsInfo.make("float32", f"3:{kp}") if want_decode \
        else TensorsInfo.make("float32", f"{kp}:{hm}:{hm}")
    return apply_fn, params, in_info, out_info


class DeepLabV3(nn.Module):
    """ASPP-lite segmentation over the /16 backbone, logits upsampled
    in-graph to input resolution (the HBM-stress BASELINE config)."""

    num_classes: int = 21
    width: float = 1.0
    out_size: int = 257

    @nn.compact
    def __call__(self, x):
        feat = _Backbone(width=self.width, max_stride=16)(x)
        # ASPP-lite: 1x1 + global-pool branches (tflite-deeplab style)
        b0 = ConvBN(256)(feat)
        gp = jnp.mean(feat, axis=(1, 2), keepdims=True)
        gp = ConvBN(256)(gp)
        gp = jnp.broadcast_to(gp, b0.shape)
        h = ConvBN(256)(jnp.concatenate([b0, gp], axis=-1))
        logits = nn.Conv(self.num_classes, (1, 1),
                         dtype=jnp.float32)(h.astype(jnp.float32))
        return jax.image.resize(
            logits, (logits.shape[0], self.out_size, self.out_size,
                     self.num_classes), method="bilinear")


@register_model("deeplab_v3")
def _build_deeplab(width: str = "1.0", size: str = "257",
                   num_classes: str = "21", seed: str = "0",
                   argmax: str = "0"):
    """``argmax=1`` folds the per-pixel argmax into the XLA program and
    emits the int32 [H, W] class map instead of [H, W, C] logits — 21x
    less D2H traffic; ``argmax=u8`` goes further and emits uint8 (class
    count is <=255 by construction), another 4x off the host link.
    image_segment consumes any form (like the tflite deeplab variants
    that end in ArgMax)."""
    w, hw, nc = float(width), int(size), int(num_classes)
    want_argmax = argmax not in ("0", "", "false")
    argmax_dtype = jnp.uint8 if argmax == "u8" else jnp.int32
    if argmax == "u8" and nc > 255:
        raise ValueError(
            f"deeplab_v3: argmax=u8 cannot represent {nc} classes; "
            "use argmax=1 (int32)")
    model = DeepLabV3(num_classes=nc, width=w, out_size=hw)
    dummy = jnp.zeros((1, hw, hw, 3), jnp.bfloat16)
    params = jit_init(model, seed, dummy)

    def apply_fn(p, frame):
        batched = frame.ndim == 4
        x = frame.astype(jnp.bfloat16) / 127.5 - 1.0
        out = model.apply(p, x if batched else x[None])
        if want_argmax:
            out = jnp.argmax(out, axis=-1).astype(argmax_dtype)
        return out if batched else out[0]

    in_info = TensorsInfo.make("uint8", f"3:{hw}:{hw}")
    out_info = TensorsInfo.make(
        "uint8" if argmax == "u8" else "int32", f"{hw}:{hw}") \
        if want_argmax else TensorsInfo.make("float32", f"{nc}:{hw}:{hw}")
    return apply_fn, params, in_info, out_info
