"""MobileNet-v2 — the benchmark north-star model (flax.linen).

The reference's headline accuracy/golden pipeline is MobileNet-v1/v2 quant
TFLite image labeling (ref: tests/nnstreamer_filter_tensorflow2_lite/
runTest.sh:77-80, models in tests/test_models/models/). Here the model is a
native flax module compiled by XLA for the MXU: convolutions run in
bfloat16, the classifier emits float32 logits.

Zoo entry: ``model=zoo://mobilenet_v2?width=1.0&num_classes=1001``.
apply_fn takes one unbatched uint8 HWC frame (the pipeline's per-buffer
invoke model) and returns a [num_classes] logit vector.
"""
from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensors.info import TensorsInfo
from .zoo import jit_init, register_model

# (expansion t, channels c, repeats n, stride s) — the standard v2 table
_V2_BLOCKS: Sequence[Tuple[int, int, int, int]] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int] = (1, 1)
    strides: Tuple[int, int] = (1, 1)
    groups: int = 1
    act: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel, self.strides, padding="SAME",
                    feature_group_count=self.groups, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.99,
                         epsilon=1e-3, dtype=self.dtype)(x)
        if self.act:
            x = jnp.minimum(jax.nn.relu(x), 6.0)  # relu6
        return x


class InvertedResidual(nn.Module):
    features: int
    strides: Tuple[int, int]
    expand: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        inp = x.shape[-1]
        h = x
        if self.expand != 1:
            h = ConvBN(inp * self.expand, dtype=self.dtype)(h, train)
        h = ConvBN(inp * self.expand if self.expand != 1 else inp,
                   kernel=(3, 3), strides=self.strides,
                   groups=h.shape[-1], dtype=self.dtype)(h, train)
        h = ConvBN(self.features, act=False, dtype=self.dtype)(h, train)
        if self.strides == (1, 1) and inp == self.features:
            h = h + x
        return h


class MobileNetV2(nn.Module):
    num_classes: int = 1001
    width: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        c0 = _make_divisible(32 * self.width)
        x = ConvBN(c0, kernel=(3, 3), strides=(2, 2), dtype=self.dtype)(x, train)
        for t, c, n, s in _V2_BLOCKS:
            ch = _make_divisible(c * self.width)
            for i in range(n):
                x = InvertedResidual(
                    ch, (s, s) if i == 0 else (1, 1), t, dtype=self.dtype)(x, train)
        last = _make_divisible(1280 * max(1.0, self.width))
        x = ConvBN(last, dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))
        return x


@register_model("mobilenet_v2")
def _build_mobilenet_v2(width: str = "1.0", num_classes: str = "1001",
                        size: str = "224", seed: str = "0",
                        top1: str = "0"):
    """uint8 HWC frame in, float32 logits out; preprocessing ((x/127.5)-1)
    is fused into the jitted graph so H2D moves uint8, not float.

    ``top1=1`` folds the class argmax into the XLA program and emits one
    int32 id per frame instead of the [classes] logits — the TPU-first
    device-decode option (like deeplab's ``argmax=u8`` and posenet's
    ``decode=device``): for a labeling pipeline only 4 bytes/frame cross
    the host link. The image_labeling decoder's logits mode stays the
    parity path."""
    w, nc, hw = float(width), int(num_classes), int(size)
    want_top1 = top1 not in ("0", "", "false")
    model = MobileNetV2(num_classes=nc, width=w)
    dummy = jnp.zeros((1, hw, hw, 3), jnp.bfloat16)
    variables = jit_init(model, seed, dummy)

    def apply_fn(params, frame):
        # batch-polymorphic: an HWC frame runs as batch-1; a BHWC stack
        # (tensor_aggregator batched invoke) runs as one MXU dispatch
        batched = frame.ndim == 4
        x = frame.astype(jnp.bfloat16) / 127.5 - 1.0
        logits = model.apply(params, x if batched else x[None])
        if want_top1:
            # keepdims: the per-frame tensor is [1] (int32 class id), so
            # batched stacks are [B, 1] — matching out_info exactly
            logits = jnp.argmax(logits, axis=-1,
                                keepdims=True).astype(jnp.int32)
        return logits if batched else logits[0]

    in_info = TensorsInfo.make("uint8", f"3:{hw}:{hw}")
    out_info = TensorsInfo.make("int32", "1") if want_top1 \
        else TensorsInfo.make("float32", str(nc))
    return apply_fn, variables, in_info, out_info
