"""Model zoo: named model builders for the jax filter backend.

``model=zoo://<name>?k=v`` resolves here. A builder returns
``(apply_fn, params, input_info, output_info)`` where ``apply_fn(params,
*inputs)`` is a pure jittable function over *unbatched* frame tensors
(builders add/remove the batch dim internally so pipeline caps stay
per-frame, matching the reference's per-buffer invoke model).

Params default to deterministic random init (seed in kwargs); pass
``params_dir=<orbax dir>`` to load trained weights.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ..tensors.info import TensorsInfo

Builder = Callable[..., Tuple[Callable, Any, Optional[TensorsInfo], Optional[TensorsInfo]]]

_ZOO: Dict[str, Builder] = {}


def register_model(name: str):
    def deco(fn: Builder) -> Builder:
        _ZOO[name] = fn
        return fn
    return deco


def build(name: str, params_dir: Optional[str] = None, **kwargs):
    if name not in _ZOO:
        raise ValueError(f"unknown zoo model {name!r}; known: {sorted(_ZOO)}")
    apply_fn, params, in_info, out_info = _ZOO[name](**kwargs)
    if params_dir is not None:
        from ..trainers.checkpoint import restore_params
        params = restore_params(params_dir, params)
    return apply_fn, params, in_info, out_info


def model_names():
    return sorted(_ZOO)


def jit_init(model, seed: str, dummy):
    """Init a flax module's params in ONE compiled dispatch.

    Eager flax init runs hundreds of tiny ops; on a remote-attached chip
    each is a full RPC round trip, turning model open into minutes under
    bad link weather. Jitting the init collapses it into one dispatch.
    """
    import jax
    return jax.jit(model.init)(jax.random.PRNGKey(int(seed)), dummy)


@register_model("toyseg")
def _build_toyseg(height: str = "8", width: str = "8", classes: str = "5",
                  seed: str = "0"):
    """Toy per-pixel segmenter: [H, W] float32 -> [H, W, C] logits via
    per-class elementwise scale+shift. Deliberately elementwise-only
    (no matmul/conv, no reductions) so its outputs are bit-exact across
    XLA fusion decisions — the model the fusion compiler's byte-parity
    oracle leans on for filter->decoder chains."""
    import jax
    import jax.numpy as jnp

    h, w, c = int(height), int(width), int(classes)
    k1, k2 = jax.random.split(jax.random.PRNGKey(int(seed)))
    params = {
        "scale": jax.random.normal(k1, (c,), jnp.float32),
        "shift": jax.random.normal(k2, (c,), jnp.float32),
    }

    def apply_fn(p, x):
        return x.astype(jnp.float32)[..., None] * p["scale"] + p["shift"]

    in_info = TensorsInfo.make("float32", f"{h}:{w}")
    out_info = TensorsInfo.make("float32", f"{h}:{w}:{c}")
    return apply_fn, params, in_info, out_info


@register_model("toyscale")
def _build_toyscale(height: str = "8", width: str = "8", classes: str = "5",
                    seed: str = "1"):
    """Elementwise per-class affine over [H, W, C] logits -> [H, W, C]
    (a toy calibration head). Chains after ``toyseg`` as the second
    link of the fusion byte-parity oracle: elementwise-only like
    toyseg, so a toyseg!toyscale segment stays bit-exact across XLA
    fusion AND mesh partitioning decisions."""
    import jax
    import jax.numpy as jnp

    h, w, c = int(height), int(width), int(classes)
    k1, k2 = jax.random.split(jax.random.PRNGKey(int(seed)))
    params = {
        "scale": jax.random.normal(k1, (c,), jnp.float32),
        "shift": jax.random.normal(k2, (c,), jnp.float32),
    }

    def apply_fn(p, x):
        return x.astype(jnp.float32) * p["scale"] + p["shift"]

    info = TensorsInfo.make("float32", f"{h}:{w}:{c}")
    return apply_fn, params, info, info.copy()


@register_model("mlp")
def _build_mlp(in_dim: str = "64", hidden: str = "128", out_dim: str = "10",
               seed: str = "0", dtype: str = "bfloat16"):
    """Tiny MLP — the zoo's passthrough-grade test model."""
    import jax
    import jax.numpy as jnp

    d_in, d_h, d_out = int(in_dim), int(hidden), int(out_dim)
    k1, k2 = jax.random.split(jax.random.PRNGKey(int(seed)))
    dt = jnp.dtype(dtype)
    params = {
        "w1": jax.random.normal(k1, (d_in, d_h), dt) * (1.0 / d_in) ** 0.5,
        "b1": jnp.zeros((d_h,), dt),
        "w2": jax.random.normal(k2, (d_h, d_out), dt) * (1.0 / d_h) ** 0.5,
        "b2": jnp.zeros((d_out,), dt),
    }

    def apply_fn(p, x):
        x = x.astype(dt)
        h = jax.nn.relu(x @ p["w1"] + p["b1"])
        return (h @ p["w2"] + p["b2"]).astype(jnp.float32)

    in_info = TensorsInfo.make("float32", str(d_in))
    out_info = TensorsInfo.make("float32", str(d_out))
    return apply_fn, params, in_info, out_info
