"""Model zoo (flax/jax model builders for the jax filter backend)."""
from . import zoo
from .zoo import build, model_names, register_model

__all__ = ["zoo", "build", "model_names", "register_model"]
