"""Model zoo (flax/jax model builders for the jax filter backend)."""
from . import zoo
from .zoo import build, model_names, register_model
from . import detection, mobilenet, transformer, vit  # noqa: F401,E402 — register zoo entries

__all__ = ["zoo", "build", "model_names", "register_model",
           "mobilenet", "transformer", "vit"]
