"""The black-box flight recorder.

Always on: the span rings (`spans.py`) plus a bounded structured-event
ring are this process's last-N-seconds of history, at the cost of ring
appends. A dump renders both into Chrome ``trace_event`` JSON
(chrome://tracing / Perfetto load it directly):

* spans -> ``"ph": "X"`` complete events, with the trace/span/parent
  ids hex-encoded in ``args`` so a span tree can be re-linked across
  the per-process dumps of a fleet;
* structured events -> ``"ph": "i"`` instant events.

Dump triggers: on demand (:meth:`FlightRecorder.dump`), on
``Pipeline.preempt()``, and on any abort (``Pipeline.post_message``
error path) — abort dumps are rate-limited so a crash-looping fleet
cannot fill a disk. Files land in ``$NNS_TPU_FLIGHT_DIR`` (default
``build/flight``); setting it empty disables the automatic dumps.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils.log import logger
from . import spans

# retention window rendered into a dump (seconds of history)
WINDOW_S = float(os.environ.get("NNS_TPU_OBS_WINDOW", "30"))
EVENT_RING = 2048
# at most one automatic abort dump per process per this many seconds
ABORT_DUMP_INTERVAL_S = 30.0


class FlightRecorder:
    """Per-process singleton (module-level :data:`RECORDER`)."""

    def __init__(self):
        self._events: deque = deque(maxlen=EVENT_RING)
        self._elock = threading.Lock()
        self._last_abort_dump = 0.0
        self._dumps = 0

    # -- event side (obs.events.emit lands here) -----------------------
    def add_event(self, kind: str, source: str, fields: Dict[str, Any]
                  ) -> None:
        if not spans.ENABLED:
            return
        with self._elock:
            self._events.append((time.time_ns(), kind, source, fields))

    def events(self, window_s: Optional[float] = None) -> List[tuple]:
        cutoff = time.time_ns() - int((window_s or WINDOW_S) * 1e9)
        with self._elock:
            return [e for e in self._events if e[0] >= cutoff]

    def event_counts(self) -> Dict[str, int]:
        with self._elock:
            evs = list(self._events)
        out: Dict[str, int] = {}
        for _ts, kind, _src, _f in evs:
            out[kind] = out.get(kind, 0) + 1
        return out

    def clear(self) -> None:
        with self._elock:
            self._events.clear()
        spans.clear()

    # -- dumping -------------------------------------------------------
    def dump(self, path: Optional[str] = None,
             window_s: Optional[float] = None,
             reason: str = "on-demand") -> Dict[str, Any]:
        """Render the last ``window_s`` seconds into a Chrome
        trace_event document; write it to ``path`` when given."""
        cutoff = time.time_ns() - int((window_s or WINDOW_S) * 1e9)
        pid = os.getpid()
        out: List[Dict[str, Any]] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"nnstreamer_tpu:{pid}"}}]
        names = spans.thread_names()
        for tid, name in names.items():
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": name}})
        for tid, s in spans.snapshot():
            name, cat, ts_ns, dur_ns, trace_id, span_id, parent = s
            if ts_ns < cutoff:
                continue
            out.append({
                "ph": "X", "name": name, "cat": cat, "pid": pid,
                "tid": tid, "ts": ts_ns / 1e3, "dur": dur_ns / 1e3,
                "args": {"trace": f"{trace_id:x}", "span": f"{span_id:x}",
                         "parent": f"{parent:x}"}})
        for ts_ns, kind, source, fields in self.events(window_s):
            out.append({
                "ph": "i", "name": kind, "cat": "event", "pid": pid,
                "tid": 0, "ts": ts_ns / 1e3, "s": "p",
                "args": dict(fields, source=source)})
        doc = {"traceEvents": out, "displayTimeUnit": "ms",
               "otherData": {"reason": reason, "pid": pid,
                             "window_s": window_s or WINDOW_S}}
        if path:
            tmp = f"{path}.tmp.{pid}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        return doc

    def dump_abort(self, reason: str, force: bool = False
                   ) -> Optional[str]:
        """The abort/preempt trigger: write a dump into the flight dir,
        rate-limited (``force=True`` for preempt, which is deliberate
        and singular). Returns the path, or None when skipped."""
        flight_dir = os.environ.get("NNS_TPU_FLIGHT_DIR", "build/flight")
        if not flight_dir or not spans.ENABLED:
            return None
        now = time.monotonic()
        with self._elock:
            if not force and \
                    now - self._last_abort_dump < ABORT_DUMP_INTERVAL_S:
                return None
            self._last_abort_dump = now
            self._dumps += 1
            n = self._dumps
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:48]
        path = os.path.join(flight_dir,
                            f"flight-{os.getpid()}-{safe}-{n}.json")
        try:
            os.makedirs(flight_dir, exist_ok=True)
            self.dump(path, reason=reason)
        except OSError as exc:
            logger.warning("flight recorder: dump to %s failed: %s",
                           path, exc)
            return None
        logger.info("flight recorder: dumped %s (%s)", path, reason)
        return path


RECORDER = FlightRecorder()
