"""``python -m nnstreamer_tpu top`` — the fleet cockpit.

Scrapes every telemetry endpoint it can find — explicit ``--targets``
plus whatever registered under ``--topic`` on a discovery broker — and
renders one table row per process: serve depth/streams/occupancy,
queue-delay p50, end-to-end latency p50-ish (from the histogram), frame
throughput, and shed/event counts. One-shot by default; ``--watch N``
redraws every N seconds (rates are computed between scrapes).
"""
from __future__ import annotations

import json
import sys
import time
from typing import Dict, List, Optional, Tuple

from . import metrics
from .server import scrape

Sample = Dict[Tuple[str, tuple], float]


def _discover(broker: str, topic: str, timeout: float
              ) -> List[Tuple[str, int]]:
    host, _, port = broker.partition(":")
    from ..edge.broker import discover_meta
    eps = []
    for (h, p), meta in discover_meta(host or "localhost",
                                      int(port or 3100), topic,
                                      timeout=timeout):
        if not meta or meta.get("role") == "obs":
            eps.append((h, p))
    return eps


def _get(samples: Sample, name: str, **match) -> float:
    """Sum every sample of ``name`` whose labels include ``match``."""
    total, hit = 0.0, False
    for (n, labels), v in samples.items():
        if n != name:
            continue
        lab = dict(labels)
        if all(lab.get(k) == str(w) for k, w in match.items()):
            total += v
            hit = True
    return total if hit else float("nan")


def _hist_p50(samples: Sample) -> float:
    """Approximate pooled p50 (ms) from the e2e histogram buckets."""
    by_le: Dict[float, float] = {}
    total = 0.0
    for (n, labels), v in samples.items():
        if n == "nns_e2e_latency_seconds_bucket":
            le = dict(labels).get("le", "+Inf")
            edge = float("inf") if le == "+Inf" else float(le)
            by_le[edge] = by_le.get(edge, 0.0) + v
        elif n == "nns_e2e_latency_seconds_count":
            total += v
    if not by_le or total <= 0:
        return float("nan")
    half = total / 2.0
    for edge in sorted(by_le):
        if by_le[edge] >= half:
            return edge * 1e3 if edge != float("inf") else float("nan")
    return float("nan")


def _row(host: str, port: int, samples: Sample,
         prev: Optional[Tuple[float, Sample]]) -> Dict[str, object]:
    frames = _get(samples, "nns_element_counter_total", counter="buffers")
    fps = float("nan")
    if prev is not None:
        t_prev, s_prev = prev
        dt = time.monotonic() - t_prev
        f_prev = _get(s_prev, "nns_element_counter_total",
                      counter="buffers")
        if dt > 0 and frames == frames and f_prev == f_prev:
            fps = max(0.0, (frames - f_prev) / dt)
    shed = sum(v for (n, labels), v in samples.items()
               if n == "nns_events_total"
               and dict(labels).get("kind") == "shed")
    return {
        "endpoint": f"{host}:{port}",
        "depth": _get(samples, "nns_serve_depth"),
        "streams": _get(samples, "nns_serve_streams"),
        "occ": _get(samples, "nns_serve_occupancy_avg"),
        "qd_p50_us": _get(samples, "nns_serve_queue_delay_us",
                          quantile="p50"),
        "e2e_p50_ms": _hist_p50(samples),
        "fps": fps,
        "shed": shed,
        "events": sum(v for (n, _), v in samples.items()
                      if n == "nns_events_total"),
    }


def _fmt(v: object) -> str:
    if isinstance(v, float):
        if v != v:
            return "-"
        return f"{v:.1f}" if abs(v) < 1e5 else f"{v:.3g}"
    return str(v)


_COLS = ("endpoint", "depth", "streams", "occ", "qd_p50_us",
         "e2e_p50_ms", "fps", "shed", "events")


def render_table(rows: List[Dict[str, object]]) -> str:
    headers = [c.upper() for c in _COLS]
    cells = [[_fmt(r.get(c)) for c in _COLS] for r in rows]
    widths = [max(len(h), *(len(row[i]) for row in cells)) if cells
              else len(h) for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in cells:
        out.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(out)


def collect(targets: List[Tuple[str, int]], timeout: float,
            prev: Dict[Tuple[str, int], Tuple[float, Sample]]
            ) -> List[Dict[str, object]]:
    rows = []
    for host, port in targets:
        try:
            samples = metrics.parse(scrape(host, port, timeout=timeout))
        except (OSError, ConnectionError) as exc:
            rows.append({"endpoint": f"{host}:{port}",
                         "events": f"unreachable ({exc})"})
            continue
        rows.append(_row(host, port, samples, prev.get((host, port))))
        prev[(host, port)] = (time.monotonic(), samples)
    return rows


def main(argv: List[str]) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m nnstreamer_tpu top",
        description="scrape a fleet's telemetry endpoints into one table")
    ap.add_argument("--targets", default="",
                    help="comma-separated host:port telemetry endpoints")
    ap.add_argument("--broker", default="",
                    help="discovery broker host:port to query for --topic")
    ap.add_argument("--topic", default="obs")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECS",
                    help="redraw every SECS seconds (0 = one-shot)")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--json", action="store_true",
                    help="emit rows as JSON instead of a table")
    args = ap.parse_args(argv)

    targets: List[Tuple[str, int]] = []
    for t in args.targets.split(","):
        t = t.strip()
        if t:
            h, _, p = t.rpartition(":")
            targets.append((h or "localhost", int(p)))
    if args.broker:
        try:
            for ep in _discover(args.broker, args.topic, args.timeout):
                if ep not in targets:
                    targets.append(ep)
        except (OSError, ConnectionError) as exc:
            print(f"top: broker {args.broker} unreachable: {exc}",
                  file=sys.stderr)
    if not targets:
        print("top: no targets (give --targets and/or --broker)",
              file=sys.stderr)
        return 2

    prev: Dict[Tuple[str, int], Tuple[float, Sample]] = {}
    while True:
        rows = collect(targets, args.timeout, prev)
        if args.json:
            print(json.dumps(rows, default=str))
        else:
            if args.watch > 0:
                print("\x1b[2J\x1b[H", end="")
            print(render_table(rows))
        if args.watch <= 0:
            return 0
        time.sleep(args.watch)
