"""Structured runtime events: one emit API for every "something
operationally notable happened" site.

``emit(kind, ...)`` is the single source of truth the satellite asks
for: it appends the event to the flight-recorder ring, writes the log
line the call sites used to hand-roll, and (when asked) posts the bus
warning — so the recorder, the log, and the bus can never drift apart.

Kinds in use: ``breaker`` (open/close flips), ``shed`` (admission /
deadline / backpressure drops), ``failover`` (router re-dispatch after
a replica death), ``drain``, ``preempt``, ``resume`` (session RESUME
replay), ``abort``.
"""
from __future__ import annotations

import logging
from typing import Any, Optional

from ..utils.log import logger
from .recorder import RECORDER


def emit(kind: str, source: str = "", *, element: Optional[Any] = None,
         level: int = logging.WARNING, message: Optional[str] = None,
         bus: Optional[str] = None, **fields) -> None:
    """Record a structured event.

    ``source`` names the emitter (element/component); ``message`` is
    the human log line (skipped when None — some sites keep their own
    richer logging); ``bus`` posts a pipeline bus message of that kind
    via ``element`` (which must then be a live pipeline element).
    """
    if element is not None and not source:
        source = getattr(element, "name", "") or ""
    RECORDER.add_event(kind, source, fields)
    if message is not None:
        logger.log(level, "%s: %s", source or kind, message)
    if bus is not None and element is not None:
        pipeline = getattr(element, "pipeline", None)
        if pipeline is not None:
            pipeline.post_message(bus, source=source, **fields)
