"""Bounded, lock-cheap per-thread span rings.

The hot path (one record per element hop per frame) touches no shared
lock: each thread appends fixed-shape tuples to its own bounded
``deque`` (C-level append, maxlen eviction). The global registry of
rings is only locked when a NEW thread records its first span and when
a dump snapshots the fleet — never per frame.

A span is the tuple::

    (name, cat, ts_ns, dur_ns, trace_id, span_id, parent_id, tid)

with wall-clock (epoch) timestamps so spans recorded in different
processes align in one Chrome trace. ``NNS_TPU_OBS=0`` turns the whole
layer off (the obs-overhead gate's control arm).
"""
from __future__ import annotations

import os
import threading
from collections import deque
from typing import List, Optional, Tuple

from .context import (CTX_KEY, TraceContext, _BASE, _IDS, _tls as _ctx_tls,
                      next_id)

# per-thread ring capacity: at ~6 spans per frame per process this
# holds many seconds of a fast pipeline's history; tune via env
RING_SPANS = int(os.environ.get("NNS_TPU_OBS_RING", "8192"))

ENABLED = os.environ.get("NNS_TPU_OBS", "1").lower() \
    not in ("0", "false", "off")


def enabled() -> bool:
    return ENABLED


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)


_tls = threading.local()
_rings: List[Tuple[int, str, deque]] = []     # (tid, thread name, ring)
_rings_lock = threading.Lock()


def _new_ring() -> deque:
    """Slow path of ``_ring()``: first span on this thread."""
    r = deque(maxlen=RING_SPANS)
    _tls.ring = r
    t = threading.current_thread()
    with _rings_lock:
        _rings.append((t.ident or 0, t.name, r))
    return r


def _ring() -> deque:
    # try/except over getattr: the hit path is free on modern CPython
    # and this runs once per recorded span
    try:
        return _tls.ring
    except AttributeError:
        return _new_ring()


def snapshot() -> List[tuple]:
    """Every live span, all threads: [(tid, span), ...]. Copying under
    the registry lock keeps concurrent appends safe (deque iteration
    over a mutating deque is not)."""
    with _rings_lock:
        rings = list(_rings)
    out = []
    for tid, _name, ring in rings:
        out.extend((tid, s) for s in list(ring))
    return out


def thread_names() -> dict:
    with _rings_lock:
        return {tid: name for tid, name, _ in _rings}


def clear() -> None:
    """Test hook: drop every recorded span (rings stay registered)."""
    with _rings_lock:
        for _tid, _name, ring in _rings:
            ring.clear()


# -- recording ----------------------------------------------------------

def record_span(name: str, cat: str, ts_ns: int, dur_ns: int,
                ctx: Optional[TraceContext] = None,
                parent: Optional[int] = None) -> int:
    """Record one span; with a context the span parents onto the
    context's current span and becomes the new current (the linear
    causality chain). Returns the span id (0 when recording is off)."""
    if not ENABLED:
        return 0
    sid = _BASE | (next(_IDS) & 0xFFFFFF)   # next_id(), inlined (hot)
    try:
        ring = _tls.ring
    except AttributeError:
        ring = _new_ring()
    if ctx is not None:
        p = ctx.span_id if parent is None else parent
        ring.append((name, cat, ts_ns, dur_ns, ctx.trace_id, sid, p))
        ctx.span_id = sid
    else:
        ring.append((name, cat, ts_ns, dur_ns, 0, sid,
                     0 if parent is None else parent))
    return sid


def record_root(name: str, ctx: TraceContext) -> int:
    """The source-stamp root span (zero duration, no parent): children
    recorded downstream always find their parent in the dump."""
    if not ENABLED:
        return 0
    sid = next_id()
    _ring().append((name, "source", ctx.t0_ns, 0, ctx.trace_id, sid, 0))
    ctx.span_id = sid
    return sid


_observe_e2e = None    # metrics.observe_e2e, bound on first sink frame


def chain_span(element, buf, ts_ns: int, dur_ns: int) -> None:
    """The per-element hop: one span per buffer through ``chain()``,
    attributed to compute. Sinks additionally settle the frame's
    end-to-end histogram. ``ensure_ctx`` + ``record_span`` are inlined:
    this is the single hottest call in the whole obs plane (once per
    element per frame) and the obs-overhead gate prices every function
    call made here."""
    extras = buf.extras
    ctx = extras.get(CTX_KEY)
    if ctx is None:                  # fresh buffer: chain-thread inherit
        ctx = getattr(_ctx_tls, "ctx", None)
        if ctx is None:
            return
        extras[CTX_KEY] = ctx
    else:
        _ctx_tls.ctx = ctx
    sid = _BASE | (next(_IDS) & 0xFFFFFF)
    try:
        ring = _tls.ring
    except AttributeError:
        ring = _new_ring()
    ring.append((element.name, "element", ts_ns, dur_ns,
                 ctx.trace_id, sid, ctx.span_id))
    ctx.span_id = sid
    ctx.c_ns += dur_ns
    if not element.src_pads:         # terminal: the frame settles here
        global _observe_e2e
        if _observe_e2e is None:
            from .metrics import observe_e2e as _obs
            _observe_e2e = _obs
        _observe_e2e(element, ctx, ts_ns + dur_ns)
