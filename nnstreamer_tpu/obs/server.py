"""The per-process telemetry pull endpoint.

A deliberately tiny HTTP/1.0 server over a plain listener socket (no
``http.server`` thread-per-request fan-out — scrapes are short and
serial, and one accept thread keeps the concurrency model trivially
auditable: racecheck seeds the SCRAPER role for ``_serve_loop``).

Routes:

* ``GET /metrics``  -> Prometheus text exposition (`metrics.render`)
* ``GET /flight``   -> the flight recorder's Chrome trace_event JSON
* ``GET /healthz``  -> ``ok``

``broker=(host, port)`` registers the endpoint on the discovery broker
under ``topic`` (default ``"obs"``) with role metadata, which is how
``python -m nnstreamer_tpu top`` finds a fleet's endpoints; the
registration connection stays open for the server's lifetime (the
broker's liveness-by-connection contract).
"""
from __future__ import annotations

import json
import socket
import threading
from typing import Dict, Optional, Tuple

from ..utils.log import logger
from . import metrics
from .recorder import RECORDER

_MAX_REQUEST = 8192
_HDR = ("HTTP/1.0 {code}\r\nContent-Type: {ctype}\r\n"
        "Content-Length: {length}\r\nConnection: close\r\n\r\n")


class MetricsServer:
    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 broker: Optional[Tuple[str, int]] = None,
                 topic: str = "obs", labels: Optional[Dict] = None,
                 timeout: float = 5.0):
        self.host = host
        self.port = int(port)
        self.broker = broker
        self.topic = topic
        self.labels = dict(labels or {})
        self.timeout = float(timeout)
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._broker_sock: Optional[socket.socket] = None
        self._stop_evt = threading.Event()
        self.scrapes = 0

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener \
            else self.port

    def start(self) -> "MetricsServer":
        self._stop_evt.clear()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        if self.broker is not None:
            from ..edge.protocol import MsgKind, send_msg
            try:
                self._broker_sock = socket.create_connection(
                    self.broker, timeout=self.timeout)
                send_msg(self._broker_sock, MsgKind.REGISTER,
                         {"topic": self.topic, "host": self.host,
                          "port": self.bound_port,
                          "meta": dict(self.labels, role="obs")})
            except OSError as exc:
                logger.warning("obs: broker registration failed: %s", exc)
                self._broker_sock = None
        self._thread = threading.Thread(
            target=self._serve_loop,
            name=f"obs-scrape:{self.bound_port}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        for s in (self._broker_sock, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._broker_sock = None
        self._listener = None
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None

    # -- the scrape loop (racecheck role: SCRAPER) ---------------------
    def _serve_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutdown
            try:
                conn.settimeout(self.timeout)
                self._handle(conn)
            except (OSError, ValueError) as exc:
                logger.info("obs: scrape connection failed: %r", exc)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _handle(self, conn: socket.socket) -> None:
        data = b""
        while b"\r\n\r\n" not in data and len(data) < _MAX_REQUEST:
            chunk = conn.recv(2048)
            if not chunk:
                return
            data += chunk
        line = data.split(b"\r\n", 1)[0].decode("latin-1", "replace")
        parts = line.split()
        path = parts[1] if len(parts) >= 2 else "/"
        path = path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = metrics.render().encode()
            ctype = "text/plain; version=0.0.4"
            code = "200 OK"
        elif path in ("/flight", "/flight.json", "/trace"):
            body = json.dumps(RECORDER.dump(reason="scrape")).encode()
            ctype = "application/json"
            code = "200 OK"
        elif path == "/healthz":
            body, ctype, code = b"ok\n", "text/plain", "200 OK"
        else:
            body, ctype, code = b"not found\n", "text/plain", \
                "404 Not Found"
        self.scrapes += 1  # racecheck: ok(single accept thread is the only writer; readers are test/diagnostic polls tolerant of a stale int)
        conn.sendall(_HDR.format(code=code, ctype=ctype,
                                 length=len(body)).encode() + body)


def scrape(host: str, port: int, path: str = "/metrics",
           timeout: float = 5.0) -> str:
    """One HTTP GET against a telemetry endpoint -> response body."""
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.sendall(f"GET {path} HTTP/1.0\r\nHost: {host}\r\n\r\n".encode())
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    head, _, body = data.partition(b"\r\n\r\n")
    status = head.split(b"\r\n", 1)[0].decode("latin-1", "replace")
    if " 200 " not in status + " ":
        raise ConnectionError(f"scrape {host}:{port}{path}: {status}")
    return body.decode("utf-8", "replace")
