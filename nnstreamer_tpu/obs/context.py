"""TraceContext: the per-frame identity a span tree hangs off.

One context object rides in ``Buffer.extras[CTX_KEY]`` from the source
that stamped it to whatever finally settles the frame — across queue
hops (extras survive the queue), element rewrites (``copy_meta_from`` /
``with_chunks`` copy extras; elements that mint fresh buffers inherit
the chain thread's current context, mirroring ``utils.trace``'s
birth-stamp inheritance), and wire hops (``edge.wire`` re-creates the
context on the receiving side from the negotiated trace field).

The context is deliberately mutable: each recorded span advances
``span_id`` so the next hop parents onto it — frame causality is a
linear chain per process, forked only by explicit links (batch
adoption, overlap completion). The ``q_ns``/``c_ns``/``w_ns``
accumulators attribute the frame's end-to-end latency to queue wait,
compute, and wire time; they cross process boundaries inside the wire
trace field so the final sink's histogram sees the whole journey.
"""
from __future__ import annotations

import itertools
import random
import threading
import time
from typing import Optional

# extras key; must not collide with utils.trace's "_trace*" namespace
# (test_trace pins that tracing-off leaves no "_trace" keys behind)
CTX_KEY = "_obs_ctx"
# queue-entry wall stamp (pipeline/basic.py Queue): set on put, consumed
# on the worker's pop to record the queue-wait span
QT_KEY = "_obs_qns"

# id allocation: a per-process random 63-bit base with a low 24-bit
# counter — unique across the fleet without paying getrandbits() per
# frame. itertools.count.__next__ is atomic under the GIL.
_BASE = random.getrandbits(63) & ~0xFFFFFF
_IDS = itertools.count(1)


def next_id() -> int:
    return _BASE | (next(_IDS) & 0xFFFFFF)


class TraceContext:
    """(trace_id, current span) + latency attribution accumulators."""

    __slots__ = ("trace_id", "span_id", "t0_ns", "q_ns", "c_ns", "w_ns")

    def __init__(self, trace_id: int, span_id: int, t0_ns: int,
                 q_ns: int = 0, c_ns: int = 0, w_ns: int = 0):
        self.trace_id = trace_id
        self.span_id = span_id       # most recent span = next hop's parent
        self.t0_ns = t0_ns           # birth wall time (epoch ns)
        self.q_ns = q_ns             # queue-wait attribution
        self.c_ns = c_ns             # compute attribution
        self.w_ns = w_ns             # wire attribution

    def child(self) -> "TraceContext":
        """Fork for a derived frame (batch adoption): same trace, same
        parent span, fresh accumulators."""
        return TraceContext(self.trace_id, self.span_id, self.t0_ns)

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id:#x}, span={self.span_id:#x}, "
                f"q={self.q_ns} c={self.c_ns} w={self.w_ns})")

    # pickle support for checkpointed buffers (slots, no __dict__)
    def __getstate__(self):
        return (self.trace_id, self.span_id, self.t0_ns,
                self.q_ns, self.c_ns, self.w_ns)

    def __setstate__(self, state):
        (self.trace_id, self.span_id, self.t0_ns,
         self.q_ns, self.c_ns, self.w_ns) = state


# chain-thread inheritance for elements that mint fresh buffers
# (converter, mux, aggregator, decoders): the last context seen on this
# thread re-attaches, exactly like utils.trace's birth inheritance
_tls = threading.local()


def ctx_of(buf) -> Optional[TraceContext]:
    return buf.extras.get(CTX_KEY)


def ensure_ctx(buf) -> Optional[TraceContext]:
    """The chain-path lookup: the buffer's own context, else the chain
    thread's inherited one (re-attached), else None."""
    ctx = buf.extras.get(CTX_KEY)
    if ctx is None:
        ctx = getattr(_tls, "ctx", None)
        if ctx is not None:
            buf.extras[CTX_KEY] = ctx
    else:
        _tls.ctx = ctx
    return ctx


def stamp(buf) -> TraceContext:
    """Source-side root: mint a fresh trace for this frame (the root
    span itself is recorded by the caller so children never dangle)."""
    ctx = TraceContext(next_id(), 0, time.time_ns())
    buf.extras[CTX_KEY] = ctx
    _tls.ctx = ctx
    return ctx


def attach(buf, ctx: TraceContext) -> None:
    buf.extras[CTX_KEY] = ctx


# -- wire encoding ------------------------------------------------------
# The DATA-meta trace field: [trace_id, span_id, t_send_ns, t0_ns,
# q_ns, c_ns, w_ns]. Only emitted on links that negotiated trace
# (wire.WireConfig.trace), so old peers see byte-identical traffic.

def to_wire(ctx: TraceContext) -> list:
    return [ctx.trace_id, ctx.span_id, time.time_ns(), ctx.t0_ns,
            ctx.q_ns, ctx.c_ns, ctx.w_ns]


def from_wire(field) -> Optional[tuple]:
    """-> (ctx_without_wire_span, t_send_ns) or None on a malformed
    field (a hostile/buggy peer must not take the pipeline down)."""
    try:
        tid, sid, t_send, t0, q, c, w = (int(x) for x in field)
    except (TypeError, ValueError):
        return None
    if tid == 0:
        return None
    return TraceContext(tid, sid, t0, q, c, w), t_send
