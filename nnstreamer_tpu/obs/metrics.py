"""Prometheus-style text exposition of the runtime's live state.

One scrape renders, in the standard ``name{labels} value`` text format:

* **end-to-end latency histograms** per (pipeline, sink) — fed by the
  span layer when a frame settles at a terminal element — plus the
  frame's queue/compute/wire attribution as monotonic seconds counters
  (``rate(nns_e2e_queue_seconds_total)`` / ``rate(..._count)`` = mean
  queue share, the autoscaler's signal);
* every per-element ``Counters`` snapshot of every registered pipeline;
* every ``ServeScheduler``'s occupancy gauges and queue-delay /
  batch-latency ``Reservoir`` percentiles (live, the series ROADMAP's
  autoscaler item polls);
* when a pipeline has a tracer attached, the full ``trace.report()``
  flattened leaf-by-leaf — every Counters/Reservoir the tracer already
  aggregates becomes a scrapeable series;
* flight-recorder structured-event counts by kind.

Pipelines register at ``start()`` and unregister at ``stop()``
(weakly — a dropped pipeline never pins itself here).
"""
from __future__ import annotations

import re
import threading
import weakref
from typing import Dict, List, Optional, Tuple

# log-ish bucket ladder (seconds) for end-to-end frame latency: sub-ms
# local pipelines through multi-second cold paths
E2E_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
               0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Fixed-bucket counting histogram (cumulative on render, plain
    per-bucket counts internally). One leaf lock; observe is O(len)."""

    def __init__(self, buckets: Tuple[float, ...] = E2E_BUCKETS):
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)   # +1: overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = 0
        for i, edge in enumerate(self.buckets):
            if value <= edge:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """-> (cumulative counts per bucket + +Inf, sum, count)."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return cum, total, n


class _E2E:
    __slots__ = ("hist", "q_s", "c_s", "w_s", "frames")

    def __init__(self):
        self.hist = Histogram()
        self.q_s = 0.0
        self.c_s = 0.0
        self.w_s = 0.0
        self.frames = 0


_lock = threading.Lock()
_e2e: Dict[Tuple[str, str], _E2E] = {}
_pipelines: "weakref.WeakSet" = weakref.WeakSet()


def observe_e2e(element, ctx, now_ns: int) -> None:
    """A frame settled at a terminal element: feed its end-to-end
    latency and attribution (called from the span layer, once per frame
    — the registry lookup is cached on the element so the steady state
    pays one histogram lock and nothing else)."""
    try:
        ent = element._obs_e2e
    except AttributeError:
        pname = getattr(getattr(element, "pipeline", None),
                        "name", "") or ""
        with _lock:
            ent = _e2e.setdefault((pname, element.name), _E2E())
        element._obs_e2e = ent
    ent.hist.observe(max(0, now_ns - ctx.t0_ns) * 1e-9)
    # attribution counters are scrape-side aggregates; racing adds may
    # drop a sample's worth of precision, never corrupt (floats)
    ent.q_s += ctx.q_ns * 1e-9
    ent.c_s += ctx.c_ns * 1e-9
    ent.w_s += ctx.w_ns * 1e-9
    ent.frames += 1


def register_pipeline(pipeline) -> None:
    with _lock:
        _pipelines.add(pipeline)


def unregister_pipeline(pipeline) -> None:
    with _lock:
        _pipelines.discard(pipeline)


def reset() -> None:
    """Test hook; call between pipelines (elements of a still-running
    pipeline keep feeding their cached entry, not the fresh registry)."""
    with _lock:
        _e2e.clear()


# -- rendering ----------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _san(s: str) -> str:
    return _NAME_RE.sub("_", str(s))


def _esc(s: str) -> str:
    return str(s).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _labels(**kv) -> str:
    inner = ",".join(f'{_san(k)}="{_esc(v)}"' for k, v in kv.items())
    return "{" + inner + "}" if inner else ""


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _flatten(prefix: str, obj, out: List[Tuple[str, float]]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}/{k}" if prefix else str(k), v, out)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _flatten(f"{prefix}/{i}", v, out)
    else:
        n = _num(obj)
        if n is not None:
            out.append((prefix, n))


def render() -> str:
    """The full exposition document (text/plain; version=0.0.4)."""
    lines: List[str] = []

    # 1) end-to-end latency histograms + attribution
    with _lock:
        e2e = dict(_e2e)
        pipelines = list(_pipelines)
    if e2e:
        lines.append("# HELP nns_e2e_latency_seconds end-to-end frame "
                     "latency, source stamp to terminal sink")
        lines.append("# TYPE nns_e2e_latency_seconds histogram")
        for (pname, sink), ent in sorted(e2e.items()):
            cum, total, n = ent.hist.snapshot()
            for edge, c in zip(ent.hist.buckets, cum):
                lines.append(
                    f"nns_e2e_latency_seconds_bucket"
                    f'{_labels(pipeline=pname, sink=sink, le=repr(edge))}'
                    f" {c}")
            lines.append(f"nns_e2e_latency_seconds_bucket"
                         f'{_labels(pipeline=pname, sink=sink, le="+Inf")}'
                         f" {cum[-1]}")
            lines.append(f"nns_e2e_latency_seconds_sum"
                         f"{_labels(pipeline=pname, sink=sink)} {total}")
            lines.append(f"nns_e2e_latency_seconds_count"
                         f"{_labels(pipeline=pname, sink=sink)} {n}")
        lines.append("# TYPE nns_e2e_queue_seconds_total counter")
        lines.append("# TYPE nns_e2e_compute_seconds_total counter")
        lines.append("# TYPE nns_e2e_wire_seconds_total counter")
        for (pname, sink), ent in sorted(e2e.items()):
            lab = _labels(pipeline=pname, sink=sink)
            lines.append(f"nns_e2e_queue_seconds_total{lab} {ent.q_s}")
            lines.append(f"nns_e2e_compute_seconds_total{lab} {ent.c_s}")
            lines.append(f"nns_e2e_wire_seconds_total{lab} {ent.w_s}")

    # 2) per-element counters of every registered pipeline
    emitted_counter_type = False
    emitted_jit_type = False
    for p in pipelines:
        pname = getattr(p, "name", "") or ""
        for e in getattr(p, "elements", {}).values():
            try:
                snap = e.stats.snapshot()
            except Exception:  # noqa: BLE001 — a scrape never takes the runtime down
                continue
            for k, v in sorted(snap.items()):
                n = _num(v)
                if n is None:
                    continue
                if not emitted_counter_type:
                    lines.append("# TYPE nns_element_counter_total counter")
                    emitted_counter_type = True
                lines.append(
                    f"nns_element_counter_total"
                    f"{_labels(pipeline=pname, element=e.name, counter=k)}"
                    f" {n}")
                if k == "jit_recompiles":
                    # first-class family: frame-path compiles per filter
                    # (jitcheck's runtime contract — zero once warm)
                    if not emitted_jit_type:
                        lines.append(
                            "# TYPE nns_jit_recompiles_total counter")
                        emitted_jit_type = True
                    lines.append(
                        f"nns_jit_recompiles_total"
                        f"{_labels(pipeline=pname, element=e.name)} {n}")

    # 3) serve schedulers: live occupancy gauges + reservoir quantiles
    from ..serve.scheduler import SERVE_TABLE, _TABLE_LOCK
    with _TABLE_LOCK:
        scheds = dict(SERVE_TABLE)
    if scheds:
        lines.append("# TYPE nns_serve_depth gauge")
        lines.append("# TYPE nns_serve_streams gauge")
        lines.append("# TYPE nns_serve_occupancy_avg gauge")
        lines.append("# TYPE nns_serve_queue_delay_us gauge")
        lines.append("# TYPE nns_serve_batch_latency_us gauge")
    for sid, sched in sorted(scheds.items(), key=lambda kv: str(kv[0])):
        try:
            occ = sched.occupancy()
            rep = sched.report()
        except Exception:  # noqa: BLE001 — a scrape never takes the runtime down
            continue
        lab = _labels(serve=sid, name=sched.name)
        lines.append(f"nns_serve_depth{lab} {occ['depth']}")
        lines.append(f"nns_serve_streams{lab} {occ['streams']}")
        lines.append(f"nns_serve_occupancy_avg{lab} {occ['occupancy_avg']}")
        for q, v in sorted(rep.get("queue_delay_us", {}).items()):
            lines.append(
                f"nns_serve_queue_delay_us"
                f"{_labels(serve=sid, name=sched.name, quantile=q)} {v}")
        for q, v in sorted(rep.get("batch_latency_us", {}).items()):
            lines.append(
                f"nns_serve_batch_latency_us"
                f"{_labels(serve=sid, name=sched.name, quantile=q)} {v}")

    # 3b) KV block pools (paged LLM serving): occupancy is the
    # admission budget, the hit ratio is the prefix cache earning (or
    # not earning) its blocks
    from ..filters.kvpool import POOL_TABLE, _POOL_LOCK
    with _POOL_LOCK:
        pools = dict(POOL_TABLE)
    if pools:
        lines.append("# TYPE nns_kv_blocks_free gauge")
        lines.append("# TYPE nns_kv_blocks_used gauge")
        lines.append("# TYPE nns_kv_blocks_cached gauge")
        lines.append("# TYPE nns_kv_prefix_hit_ratio gauge")
        lines.append("# TYPE nns_kv_prefix_evictions_total counter")
    for pname, pool in sorted(pools.items()):
        try:
            d = pool.stats_dict()
        except Exception:  # noqa: BLE001 — a scrape never takes the runtime down
            continue
        lab = _labels(pool=pname)
        lines.append(f"nns_kv_blocks_free{lab} {d['blocks_free']}")
        lines.append(f"nns_kv_blocks_used{lab} {d['blocks_used']}")
        lines.append(f"nns_kv_blocks_cached{lab} {d['blocks_cached']}")
        lines.append(
            f"nns_kv_prefix_hit_ratio{lab} {d['prefix_hit_ratio']:.6f}")
        lines.append(
            f"nns_kv_prefix_evictions_total{lab} {d['prefix_evictions']}")

    # 3c) delta transport: the wire codec's keyframe/diff economics plus
    # the compute-skip gate, aggregated across every registered pipeline
    # — the fleet-level "bytes and invokes we did not pay for" series
    delta = {"keyframes": 0, "diffs": 0, "promotions": 0, "bytes_saved": 0,
             "frames_skipped": 0, "tiles_skipped": 0, "tiles_total": 0}
    for p in pipelines:
        for e in getattr(p, "elements", {}).values():
            try:
                snap = e.stats.snapshot()
            except Exception:  # noqa: BLE001 — a scrape never takes the runtime down
                continue
            delta["keyframes"] += snap.get("wire_delta_keyframes", 0)
            delta["diffs"] += snap.get("wire_delta_diffs", 0)
            delta["promotions"] += snap.get("wire_delta_promotions", 0)
            delta["bytes_saved"] += snap.get("wire_delta_bytes_saved", 0)
            delta["frames_skipped"] += snap.get("delta_frames_skipped", 0)
            delta["tiles_skipped"] += snap.get("delta_tiles_skipped", 0)
            delta["tiles_total"] += snap.get("delta_tiles_total", 0)
    if any(delta.values()):
        for key, val in delta.items():
            lines.append(f"# TYPE nns_delta_{key} gauge")
            lines.append(f"nns_delta_{key} {val}")

    # 3d) elastic fleet: live autoscalers expose the replica lifecycle
    # (the conservation identity's terms) as per-state gauges — what a
    # dashboard needs to see scale events and in-progress rollouts
    from ..fleet.autoscaler import live_autoscalers
    autos = live_autoscalers()
    if autos:
        lines.append("# TYPE nns_fleet_replicas gauge")
        lines.append("# TYPE nns_fleet_lifecycle_total counter")
    for auto in sorted(autos, key=lambda a: a.name):
        try:
            states = auto.replicas()
            life = auto.lifecycle()
        except Exception:  # noqa: BLE001 — a scrape never takes the runtime down
            continue
        by_state: Dict[str, int] = {}
        for st in states.values():
            by_state[st] = by_state.get(st, 0) + 1
        for st in ("serving", "draining", "resurrecting"):
            lines.append(
                f"nns_fleet_replicas"
                f"{_labels(autoscaler=auto.name, state=st)}"
                f" {by_state.get(st, 0)}")
        for k, v in sorted(life.items()):
            n = _num(v)
            if n is None:
                continue
            lines.append(
                f"nns_fleet_lifecycle_total"
                f"{_labels(autoscaler=auto.name, counter=k)} {n}")

    # 4) attached tracers: the full report, flattened — every
    # Counters/Reservoir trace.py aggregates becomes a series
    emitted_trace_type = False
    for p in pipelines:
        tracer = getattr(p, "tracer", None)
        if tracer is None:
            continue
        try:
            rep = tracer.report(p)
        except Exception:  # noqa: BLE001 — a scrape never takes the runtime down
            continue
        flat: List[Tuple[str, float]] = []
        _flatten("", rep, flat)
        pname = getattr(p, "name", "") or ""
        for path, v in flat:
            if not emitted_trace_type:
                lines.append("# TYPE nns_trace gauge")
                emitted_trace_type = True
            lines.append(
                f"nns_trace{_labels(pipeline=pname, path=path)} {v}")

    # 5) flight-recorder structured events by kind
    from .recorder import RECORDER
    counts = RECORDER.event_counts()
    if counts:
        lines.append("# TYPE nns_events_total counter")
        for kind, n in sorted(counts.items()):
            lines.append(f"nns_events_total{_labels(kind=kind)} {n}")

    return "\n".join(lines) + "\n"


# -- scrape-side parsing (the `top` CLI reuses it) ----------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+([^\s]+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Parse a text exposition back into {(name, ((k, v), ...)): value}."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, rawlab, val = m.groups()
        labels = tuple(sorted(
            (k, v.replace('\\"', '"').replace("\\\\", "\\"))
            for k, v in _LABEL_RE.findall(rawlab or "")))
        try:
            out[(name, labels)] = float(val)
        except ValueError:
            continue
    return out
