"""Frame-level observability: spans, the flight recorder, and the
telemetry plane (≙ the reference's GstTracer latency/stats hooks plus
the debug-category layer, grown into a fleet-wide plane).

Three always-on layers, cheap enough to never turn off:

* **frame spans** (`context.py` + `spans.py`) — every source stamps a
  :class:`~.context.TraceContext` into ``Buffer.extras``; every element
  hop, queue wait, wire hop, overlap dispatch/completion, and serve
  batch records a span into a bounded per-thread ring. Wire hops carry
  the context in DATA meta / the DATA_BATCH per-frame header, but only
  on links that negotiated it (wire-v2 style) — old peers see
  byte-identical traffic.
* **flight recorder** (`recorder.py` + `events.py`) — the last N
  seconds of spans plus structured events (shed, breaker flips,
  failover, RESUME, preemption), dumped to Chrome ``trace_event`` JSON
  on demand, on ``Pipeline.preempt()``, and on any abort.
* **telemetry plane** (`metrics.py` + `server.py` + `top.py`) — a pull
  endpoint per process serving Prometheus-style text exposition of the
  runtime's counters/reservoirs plus end-to-end latency histograms
  with queue/compute/wire attribution, and ``python -m nnstreamer_tpu
  top`` to scrape a fleet into one table.

``NNS_TPU_OBS=0`` disables recording entirely (the overhead gate's
control arm); everything else defaults on.
"""
from __future__ import annotations

from . import events  # noqa: F401  (re-export: obs.events.emit)
from .context import (CTX_KEY, TraceContext, ctx_of, ensure_ctx,  # noqa: F401
                      stamp)
from .recorder import RECORDER, FlightRecorder  # noqa: F401
from .spans import enabled, record_span, set_enabled  # noqa: F401


def serve_metrics(port: int = 0, host: str = "127.0.0.1",
                  broker: object = None, topic: str = "obs",
                  labels: dict = None):
    """Start this process's telemetry pull endpoint (lazy import so the
    hot span path never pays for the server module)."""
    from .server import MetricsServer
    srv = MetricsServer(port=port, host=host, broker=broker, topic=topic,
                        labels=labels)
    srv.start()
    return srv
