"""flowmarks — zero-cost acquire/settle annotations for flowcheck.

The flow analyzer (``nnstreamer_tpu.analysis.flow``) builds its
acquire/settle model from two sources: name-based seeding (regexes over
receiver names, for code that predates the analyzer) and these explicit
decorators. Decorating a method registers its NAME with the named
resource, so call sites like ``self.mgr.alloc(...)`` are recognized as
minting (or settling) a token of that resource — the spec's receiver
regex still scopes which call sites count, so ``lock.release()`` never
masquerades as a KV-block settle.

The decorators are identity functions at runtime: no wrapper frame, no
import cost beyond this module, no behavior change. They live in utils
(dependency-free) rather than in the analysis package so annotating a
leaf module like ``filters/kvpool.py`` can never create an import
cycle through the analyzer's own dependencies.

Usage::

    from ..utils import flowmarks as flow

    class KVBlockPool:
        @flow.acquires("kv-block")
        def alloc(self, n): ...

        @flow.settles("kv-block")
        def release(self, blocks): ...
"""
from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)


def acquires(resource: str) -> Callable[[F], F]:
    """Mark a function/method as minting one token of ``resource`` per
    call. flowcheck's scanner reads the decoration statically; at
    runtime this returns the function unchanged."""

    def mark(fn: F) -> F:
        return fn

    return mark


def settles(resource: str, kind: str = "ok") -> Callable[[F], F]:
    """Mark a function/method as settling a token of ``resource``.
    ``kind="loss"`` declares a lossy settle (the payload is discarded):
    flowcheck then requires the calling path to also increment one of
    the resource's declared loss counters."""

    def mark(fn: F) -> F:
        return fn

    return mark
