"""Model URI resolver: the ML-Agent bridge slot.

≙ gst/nnstreamer/ml_agent.c — the reference resolves
``mlagent://model/<name>/<version>`` URIs to file paths by asking the
Tizen mlops-agent D-Bus service, so pipelines name models instead of
hardcoding paths. Here the registry is in-process (register via API)
plus a config tier: ``[models]`` entries in the ini file
(``name = /path`` or ``name/2 = /path``).

``tensor_filter model=model://mobilenet`` resolves through this table;
unknown schemes/plain paths pass through untouched.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

_lock = threading.Lock()
# (name, version) -> path; version None = latest registered
_registry: Dict[Tuple[str, Optional[str]], str] = {}


def register_model(name: str, path: str,
                   version: Optional[str] = None) -> None:
    with _lock:
        _registry[(name, version)] = path
        _registry[(name, None)] = path  # newest registration wins "latest"


def unregister_model(name: str, version: Optional[str] = None) -> None:
    with _lock:
        if version is None:
            for key in [k for k in _registry if k[0] == name]:
                del _registry[key]
            return
        removed = _registry.pop((name, version), None)
        # keep the "latest" alias honest: repoint it at a surviving
        # version, or drop it with the last one
        if removed is not None and _registry.get((name, None)) == removed:
            def _vkey(v: str):
                # numeric-aware: version "10" outranks "9"
                return (0, int(v)) if v.isdigit() else (1, v)
            left = sorted((k[1] for k in _registry
                           if k[0] == name and k[1] is not None), key=_vkey)
            if left:
                _registry[(name, None)] = _registry[(name, left[-1])]
            else:
                _registry.pop((name, None), None)


def resolve(uri: str) -> str:
    """``model://name[/version]`` (or the reference's
    ``mlagent://model/name[/version]``) -> registered path; everything
    else passes through."""
    for prefix in ("model://", "mlagent://model/"):
        if uri.startswith(prefix):
            rest = uri[len(prefix):].strip("/")
            name, _, version = rest.partition("/")
            key = (name, version or None)
            with _lock:
                path = _registry.get(key)
            if path is None:
                path = _from_conf(name, version or None)
            if path is None:
                raise ValueError(
                    f"model URI {uri!r}: no model {name!r}"
                    f"{' v' + version if version else ''} registered")
            return path
    return uri


def _from_conf(name: str, version: Optional[str]) -> Optional[str]:
    from .conf import conf
    key = f"{name}/{version}" if version else name
    return conf.get("models", key) or None
