"""Hardware capability probe.

≙ gst/nnstreamer/hw_accel.c (NEON/SIMD detection via getauxval) — the
TPU-native version surfaces the accelerator fleet (jax.devices(): kind,
count, per-device memory stats) alongside host SIMD flags from
/proc/cpuinfo, and answers the filter ABI's CHECK_HW_AVAILABILITY
event.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List


@functools.lru_cache(maxsize=1)
def cpu_simd_flags() -> List[str]:
    """Host vector-ISA flags (≙ accl_available neon/sse checks)."""
    wanted = {"neon", "asimd", "sse", "sse2", "sse4_1", "sse4_2",
              "avx", "avx2", "avx512f", "amx_tile"}
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("flags", "features")):
                    present = set(line.split(":", 1)[1].split())
                    return sorted(wanted & present)
    except OSError:
        pass
    return []


def accelerators() -> List[Dict[str, Any]]:
    """One entry per jax device: platform/kind/id + memory stats when
    the backend exposes them (TPU HBM usage)."""
    import jax
    out = []
    for d in jax.devices():
        entry: Dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", ""),
            "process_index": d.process_index,
        }
        try:
            stats = d.memory_stats()
            if stats:
                entry["memory"] = {
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
        except Exception:  # noqa: BLE001 -- optional per backend
            pass
        out.append(entry)
    return out


def capabilities() -> Dict[str, Any]:
    """Full probe result; cheap after the first call (jax caches its
    backend)."""
    accs = accelerators()
    return {
        "accelerators": accs,
        "num_devices": len(accs),
        "default_platform": accs[0]["platform"] if accs else "none",
        "cpu_simd": cpu_simd_flags(),
    }


def is_available(kind: str) -> bool:
    """CHECK_HW_AVAILABILITY answer: is an accelerator of this kind
    (``tpu``/``gpu``/``cpu``/``default``) usable?"""
    import jax
    kind = (kind or "default").lower()
    if kind in ("default", "any"):
        return True
    if kind in ("cpu", "gpu", "tpu"):
        # ask the named backend directly: jax.devices() only lists the
        # default platform, so a TPU host would wrongly report no CPU
        try:
            return len(jax.devices(kind)) > 0
        except RuntimeError:
            return False
    return any(a["platform"].lower() == kind or
               kind in a["kind"].lower() for a in accelerators())
