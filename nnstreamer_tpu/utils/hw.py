"""Hardware capability probe.

≙ gst/nnstreamer/hw_accel.c (NEON/SIMD detection via getauxval) — the
TPU-native version surfaces the accelerator fleet (jax.devices(): kind,
count, per-device memory stats) alongside host SIMD flags from
/proc/cpuinfo, and answers the filter ABI's CHECK_HW_AVAILABILITY
event.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List


@functools.lru_cache(maxsize=1)
def cpu_simd_flags() -> List[str]:
    """Host vector-ISA flags (≙ accl_available neon/sse checks)."""
    wanted = {"neon", "asimd", "sse", "sse2", "sse4_1", "sse4_2",
              "avx", "avx2", "avx512f", "amx_tile"}
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("flags", "features")):
                    present = set(line.split(":", 1)[1].split())
                    return sorted(wanted & present)
    except OSError:
        pass
    return []


def accelerators() -> List[Dict[str, Any]]:
    """One entry per jax device: platform/kind/id + memory stats when
    the backend exposes them (TPU HBM usage)."""
    import jax
    out = []
    for d in jax.devices():
        entry: Dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", ""),
            "process_index": d.process_index,
        }
        try:
            stats = d.memory_stats()
            if stats:
                entry["memory"] = {
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
        except Exception:  # noqa: BLE001 -- optional per backend
            pass
        out.append(entry)
    return out


def capabilities() -> Dict[str, Any]:
    """Full probe result; cheap after the first call (jax caches its
    backend)."""
    accs = accelerators()
    return {
        "accelerators": accs,
        "num_devices": len(accs),
        "default_platform": accs[0]["platform"] if accs else "none",
        "cpu_simd": cpu_simd_flags(),
    }


# peak dense bf16 TFLOPS per JAX DEVICE by device-kind substring,
# checked in order (first match wins — "v5 lite" must match before
# "v5"). Public per-chip figures: v2 45, v3 123, v4 275, v5e 197,
# v5p 459, v6e 918 — but on v2/v3 jax.devices() enumerates TensorCores
# (2 per chip) and a single-device jit runs on ONE core, so those
# entries carry the per-core half to keep MFU honest.
_PEAK_BF16_TFLOPS = (
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5 lite", 197.0), ("v5litepod", 197.0), ("v5e", 197.0),
    ("v5p", 459.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 61.5), ("v2", 22.5),
)


def _match_peak(table, device, scale: float):
    """Shared device-kind lookup for the peak tables: resolve the
    device, require TPU, first substring match wins (the one place the
    'v5 lite before v5' ordering rule lives)."""
    import jax
    d = device if device is not None else jax.devices()[0]
    if d.platform != "tpu":
        return None
    kind = getattr(d, "device_kind", "").lower()
    for sub, value in table:
        if sub in kind:
            return value * scale
    return None


def peak_flops(device=None):
    """Peak dense bf16 FLOPS/s for ``device`` (default: first jax
    device), or None when the kind is unknown (e.g. CPU) — callers must
    not fabricate an MFU from a guess."""
    return _match_peak(_PEAK_BF16_TFLOPS, device, 1e12)


# peak HBM bandwidth (bytes/s) per JAX DEVICE by device-kind substring,
# same matching/convention rules as _PEAK_BF16_TFLOPS (public per-chip
# figures: v2 700 GB/s, v3 900, v4 1228, v5e 819, v5p 2765, v6e 1640;
# v2/v3 carry per-TensorCore halves since jax enumerates cores there)
_PEAK_HBM_GBPS = (
    ("v6 lite", 1640.0), ("v6e", 1640.0),
    ("v5 lite", 819.0), ("v5litepod", 819.0), ("v5e", 819.0),
    ("v5p", 2765.0), ("v5", 2765.0),
    ("v4", 1228.0), ("v3", 450.0), ("v2", 350.0),
)


def peak_membw(device=None):
    """Peak HBM bytes/s for ``device`` (default: first jax device), or
    None when unknown — callers must not fabricate an MBU from a guess.
    The honest denominator for decode-phase bandwidth utilization, the
    generation-side analog of :func:`peak_flops`."""
    return _match_peak(_PEAK_HBM_GBPS, device, 1e9)


def is_available(kind: str) -> bool:
    """CHECK_HW_AVAILABILITY answer: is an accelerator of this kind
    (``tpu``/``gpu``/``cpu``/``default``) usable?"""
    import jax
    kind = (kind or "default").lower()
    if kind in ("default", "any"):
        return True
    if kind in ("cpu", "gpu", "tpu"):
        # ask the named backend directly: jax.devices() only lists the
        # default platform, so a TPU host would wrongly report no CPU
        try:
            return len(jax.devices(kind)) > 0
        except RuntimeError:
            return False
    return any(a["platform"].lower() == kind or
               kind in a["kind"].lower() for a in accelerators())
