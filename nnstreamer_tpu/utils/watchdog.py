"""Resettable watchdog timer.

≙ nnstreamer_watchdog.c (GMainLoop-in-thread timer used for tensor_filter
``suspend`` model unloading, armed per-invoke at tensor_filter.c:1259-1266).

``feed()`` sits on the filter's hot path (called once per invoke), so it
must be cheap: one persistent thread sleeps against a monotonic deadline
and each feed just moves the deadline and notifies — no thread is ever
spawned per call (a ``threading.Timer`` per feed would create and tear
down an OS thread per frame).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .log import logger


class Watchdog:
    def __init__(self, timeout_s: float, callback: Callable[[], None]):
        self.timeout_s = timeout_s
        self.callback = callback
        self._cond = threading.Condition()
        self._deadline: Optional[float] = None   # monotonic; None = disarmed
        self._alive = True
        self._thread: Optional[threading.Thread] = None

    def feed(self) -> None:
        """(Re)arm: postpone firing by another timeout. O(1) — updates
        the deadline and wakes the (lazily created) watcher thread."""
        with self._cond:
            if not self._alive:
                return
            self._deadline = time.monotonic() + self.timeout_s
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="watchdog", daemon=True)
                self._thread.start()
            self._cond.notify_all()

    def destroy(self) -> None:
        with self._cond:
            self._alive = False
            self._deadline = None
            self._cond.notify_all()
        # no join: the callback may destroy() from the watcher thread

    def _loop(self) -> None:
        while True:
            fire = False
            with self._cond:
                if not self._alive:
                    return
                if self._deadline is None:
                    self._cond.wait()
                    continue
                now = time.monotonic()
                if now >= self._deadline:
                    self._deadline = None   # fire once, disarm until fed
                    fire = True
                else:
                    self._cond.wait(self._deadline - now)
            if fire:
                try:
                    self.callback()
                except Exception:  # noqa: BLE001 — keep the watcher alive
                    logger.warning("watchdog callback failed", exc_info=True)
