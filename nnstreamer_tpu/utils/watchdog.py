"""Resettable watchdog timer.

≙ nnstreamer_watchdog.c (GMainLoop-in-thread timer used for tensor_filter
``suspend`` model unloading, armed per-invoke at tensor_filter.c:1259-1266).
"""
from __future__ import annotations

import threading
from typing import Callable, Optional


class Watchdog:
    def __init__(self, timeout_s: float, callback: Callable[[], None]):
        self.timeout_s = timeout_s
        self.callback = callback
        self._timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()

    def feed(self) -> None:
        """(Re)arm: postpone firing by another timeout."""
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(self.timeout_s, self.callback)
            self._timer.daemon = True
            self._timer.start()

    def destroy(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
