"""Resettable watchdog timer.

≙ nnstreamer_watchdog.c (GMainLoop-in-thread timer used for tensor_filter
``suspend`` model unloading, armed per-invoke at tensor_filter.c:1259-1266).

``feed()`` sits on the filter's hot path (called once per invoke), so it
must be cheap: one persistent thread sleeps against a monotonic deadline
and each feed just moves the deadline and notifies — no thread is ever
spawned per call (a ``threading.Timer`` per feed would create and tear
down an OS thread per frame).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .log import logger


class Watchdog:
    def __init__(self, timeout_s: float, callback: Callable[[], None]):
        self.timeout_s = timeout_s
        self.callback = callback
        self._cond = threading.Condition()
        self._deadline: Optional[float] = None   # monotonic; None = disarmed
        self._quiesced = 0                       # nestable quiesce depth
        self._alive = True
        self._thread: Optional[threading.Thread] = None

    def feed(self) -> None:
        """(Re)arm: postpone firing by another timeout. O(1) — updates
        the deadline and wakes the (lazily created) watcher thread."""
        with self._cond:
            if not self._alive:
                return
            self._deadline = time.monotonic() + self.timeout_s
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="watchdog", daemon=True)
                self._thread.start()
            self._cond.notify_all()

    def quiesce(self) -> None:
        """Suspend firing during a deliberate stall (drain/replay
        flush): the dog keeps its state but cannot bite, so a
        supervised loop is never restarted — or a model unloaded —
        mid-flush. Nestable; balance every call with :meth:`resume`."""
        with self._cond:
            self._quiesced += 1
            self._cond.notify_all()

    def resume(self) -> None:
        """End one quiesce. If the deadline lapsed while quiesced, the
        dog does NOT fire retroactively — it gets a fresh full timeout
        (a long drain must never look like a stall the moment it
        ends)."""
        with self._cond:
            if self._quiesced > 0:
                self._quiesced -= 1
            if self._quiesced == 0 and self._deadline is not None:
                self._deadline = max(self._deadline,
                                     time.monotonic() + self.timeout_s)
            self._cond.notify_all()

    @property
    def quiesced(self) -> bool:
        with self._cond:
            return self._quiesced > 0

    def destroy(self) -> None:
        with self._cond:
            self._alive = False
            self._deadline = None
            self._cond.notify_all()
        # no join: the callback may destroy() from the watcher thread

    def _loop(self) -> None:
        while True:
            fire = False
            with self._cond:
                if not self._alive:
                    return
                if self._deadline is None or self._quiesced:
                    self._cond.wait()
                    continue
                now = time.monotonic()
                if now >= self._deadline:
                    self._deadline = None   # fire once, disarm until fed
                    fire = True
                else:
                    self._cond.wait(self._deadline - now)
            if fire:
                try:
                    self.callback()
                except Exception:  # noqa: BLE001 — keep the watcher alive
                    logger.warning("watchdog callback failed", exc_info=True)
