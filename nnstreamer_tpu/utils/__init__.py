"""Utilities: logging, config, watchdog."""
from .log import logger

__all__ = ["logger"]
