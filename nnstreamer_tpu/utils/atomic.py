"""Counters — a lock-guarded counter map for cross-thread stats.

``Element.stats`` (and the scheduler/batcher/breaker stat tables) are
mutated from chain threads, supervised source loops, network reader
threads and timer callbacks, while ``Pipeline.stats()`` and
``trace.report()`` read them from the user thread. A plain dict makes
every ``stats[k] += 1`` a read-modify-write race; Counters gives each
mutation one lock round-trip and gives readers a single coherent
``snapshot()``.

The internal ``_lock`` is a LEAF of the lock hierarchy: no Counters
method calls out while holding it, so it is always safe to call in
while holding any other lock. racecheck's lock-order graph records
exactly those ``Owner._lock -> Counters._lock`` edges and proves they
can never close a cycle.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, Mapping, Optional


class Counters:
    """Mapping-like atomic counter table.

    * ``inc(key)`` / ``add(**deltas)`` are the hot-path mutators: one
      lock acquisition whether you bump one key or five.
    * ``c[k]`` / ``c.get(k)`` read single values; ``snapshot()`` is the
      one consistent multi-key read.
    * Iteration / ``keys`` / ``items`` operate on a snapshot, so
      ``dict(counters)`` is coherent and never sees a mid-update table.
    """

    __slots__ = ("_lock", "_values")

    def __init__(self, initial: Optional[Mapping] = None, **keys: Any):
        self._lock = threading.Lock()
        self._values: Dict[str, Any] = dict(initial or {})
        self._values.update(keys)

    # -- mutation ----------------------------------------------------------
    def inc(self, key: str, n: int = 1) -> int:
        """Atomically add ``n`` to ``key`` (missing keys start at 0) and
        return the new value — replaces ``d[k] += 1`` AND the
        ``n = d[k] = d[k] + 1`` idiom in one step."""
        with self._lock:
            value = self._values.get(key, 0) + n
            self._values[key] = value
            return value

    def add(self, **deltas: int) -> None:
        """Atomically apply several deltas under one lock acquisition —
        the per-buffer hot path bumps buffers/bytes/proctime together."""
        with self._lock:
            values = self._values
            for key, delta in deltas.items():
                values[key] = values.get(key, 0) + delta

    def __setitem__(self, key: str, value: Any) -> None:
        with self._lock:
            self._values[key] = value

    def update(self, other: Optional[Mapping] = None, **keys: Any) -> None:
        with self._lock:
            if other:
                self._values.update(other)
            self._values.update(keys)

    # -- reads -------------------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        with self._lock:
            return self._values[key]

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._values.get(key, default)

    def snapshot(self) -> Dict[str, Any]:
        """A point-in-time copy: the only way to read several keys that
        are guaranteed to come from the same instant."""
        with self._lock:
            return dict(self._values)

    # -- mapping protocol (snapshot-backed) --------------------------------
    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._values

    def __len__(self) -> int:
        with self._lock:
            return len(self._values)

    def __iter__(self) -> Iterator[str]:
        return iter(self.snapshot())

    def keys(self):
        return self.snapshot().keys()

    def items(self):
        return self.snapshot().items()

    def values(self):
        return self.snapshot().values()

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Counters):
            return self.snapshot() == other.snapshot()
        if isinstance(other, Mapping) or isinstance(other, dict):
            return self.snapshot() == dict(other)
        return NotImplemented

    def __repr__(self) -> str:
        return f"Counters({self.snapshot()!r})"
