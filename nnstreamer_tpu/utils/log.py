"""Framework logging: leveled categories + backtrace-on-error.

≙ ml_loge/logw/logi/logd + ml_logf_stacktrace
(gst/nnstreamer/nnstreamer_log.c:35-64) and GStreamer's GST_DEBUG
per-category levels the reference elements rely on. Categories are
child loggers (``nnstreamer_tpu.<category>``); per-category levels come
from ``NNS_TPU_DEBUG``, e.g.::

    NNS_TPU_DEBUG="tensor_filter:DEBUG,mux:INFO,*:WARNING"

The global default level comes from ``NNS_TPU_LOG`` (default WARNING).
"""
from __future__ import annotations

import logging
import os
from typing import Dict

logger = logging.getLogger("nnstreamer_tpu")

_level = os.environ.get("NNS_TPU_LOG", "WARNING").upper()
if not logger.handlers:
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname).1s %(name)s %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(getattr(logging, _level, logging.WARNING))


def _parse_debug_spec(spec: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        cat, _, lvl = part.partition(":")
        level = getattr(logging, lvl.strip().upper(), None)
        if isinstance(level, int):
            out[cat.strip()] = level
    return out


_debug_spec = _parse_debug_spec(os.environ.get("NNS_TPU_DEBUG", ""))


def reload_debug_spec() -> None:
    """Re-read NNS_TPU_DEBUG (tests / live reconfiguration)."""
    global _debug_spec
    _debug_spec = _parse_debug_spec(os.environ.get("NNS_TPU_DEBUG", ""))
    for name, lg in list(_categories.items()):
        lg.setLevel(_level_for(name))


def _level_for(name: str) -> int:
    if name in _debug_spec:
        return _debug_spec[name]
    if "*" in _debug_spec:
        return _debug_spec["*"]
    return logging.NOTSET  # inherit the root framework level


_categories: Dict[str, logging.Logger] = {}


def category(name: str) -> logging.Logger:
    """Per-element/per-subsystem debug category (≙ GST_DEBUG_CATEGORY).
    Same name -> same logger; level governed by NNS_TPU_DEBUG."""
    lg = _categories.get(name)
    if lg is None:
        lg = logger.getChild(name)
        lg.setLevel(_level_for(name))
        _categories[name] = lg
    return lg


def error_with_backtrace(lg: logging.Logger, msg: str, *args) -> None:
    """Error log carrying the current Python stack
    (≙ ml_logf_stacktrace / GST_ELEMENT_ERROR_BTRACE)."""
    lg.error(msg, *args, stack_info=True)
