"""Framework logging (≙ ml_loge/logw/logi/logd macros,
ref: gst/nnstreamer/nnstreamer_log.c:35-64 -- error logs there attach a
backtrace; Python's logging.exception gives us the same for free)."""
from __future__ import annotations

import logging
import os

logger = logging.getLogger("nnstreamer_tpu")

_level = os.environ.get("NNS_TPU_LOG", "WARNING").upper()
if not logger.handlers:
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname).1s nns-tpu %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(getattr(logging, _level, logging.WARNING))
