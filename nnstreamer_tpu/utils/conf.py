"""Configuration system: ini file + environment-variable tiers.

≙ gst/nnstreamer/nnstreamer_conf.c + nnstreamer.ini.in — the reference
reads /etc/nnstreamer.ini (path overridable via NNSTREAMER_CONF), gates
env-var overrides on ``[common] enable_envvar``, and feeds framework
auto-detect priority (``framework_priority_<ext>``), subplugin search
paths, ``[filter-aliases]`` and element restriction from it.

Tiers here, lowest to highest precedence:
  1. built-in defaults
  2. ini file — ``$NNS_TPU_CONF`` if set, else ``./nnstreamer_tpu.ini``,
     else ``/etc/nnstreamer_tpu.ini``
  3. env-var overrides — honored when ``[common] enable_envvar`` is true
     (the default, and always true when no ini file exists):
       * ``NNS_TPU_FRAMEWORK_PRIORITY``            (global list, comma-sep)
       * ``NNS_TPU_FRAMEWORK_PRIORITY_<EXT>``      (per-extension list)
       * ``NNS_TPU_CUSTOMFILTERS``                 (custom .so search dirs)
       * ``NNS_TPU_FILTER_ALIASES``                ("alias=target,...")
       * ``NNS_TPU_RESTRICTED_ELEMENTS``           (allowlist, comma-sep)
"""
from __future__ import annotations

import configparser
import os
import threading
from typing import Dict, List, Optional

_DEFAULT_PATHS = ("./nnstreamer_tpu.ini", "/etc/nnstreamer_tpu.ini")

# default framework auto-detect priority when neither ini nor env override
# (≙ the hardcoded fallbacks nnstreamer_conf.c keeps for no-ini systems)
DEFAULT_PRIORITY = ["jax", "flax", "custom-easy", "python3",
                    "tensorflow-lite", "onnxruntime"]


def _split(s: str) -> List[str]:
    return [t.strip() for t in (s or "").split(",") if t.strip()]


def _parse_pairs(s: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for tok in _split(s):
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
    return out


class Conf:
    """Loaded configuration snapshot; ``reload()`` re-reads all tiers."""

    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self.reload(path)

    def reload(self, path: Optional[str] = None) -> None:
        with self._lock:
            self._ini = configparser.ConfigParser()
            self.conffile: Optional[str] = None
            candidates = ([path] if path else
                          ([os.environ["NNS_TPU_CONF"]]
                           if os.environ.get("NNS_TPU_CONF")
                           else list(_DEFAULT_PATHS)))
            for cand in candidates:
                if cand and os.path.isfile(cand):
                    self._ini.read(cand)
                    self.conffile = cand
                    break
            self.enable_envvar = self._getbool("common", "enable_envvar",
                                               default=True)

    # -- low-level accessors ------------------------------------------------
    def get(self, section: str, key: str, default: str = "") -> str:
        try:
            return self._ini.get(section, key)
        except (configparser.NoSectionError, configparser.NoOptionError):
            return default

    def _getbool(self, section: str, key: str, default: bool) -> bool:
        v = self.get(section, key, "")
        if not v:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    def _env(self, name: str) -> Optional[str]:
        if not self.enable_envvar:
            return None
        return os.environ.get(name)

    # -- framework priority ---------------------------------------------------
    def framework_priority(self, ext: str = "") -> List[str]:
        """Auto-detect priority list, most preferred first. ``ext`` is a
        model extension without the dot (e.g. ``tflite``); per-extension
        config wins over the global list
        (≙ framework_priority_tflite etc., nnstreamer.ini.in:12-19)."""
        ext = ext.lstrip(".").lower()
        if ext:
            v = self._env(f"NNS_TPU_FRAMEWORK_PRIORITY_{ext.upper()}")
            if v:
                return _split(v)
            v = self.get("filter", f"framework_priority_{ext}")
            if v:
                return _split(v)
        v = self._env("NNS_TPU_FRAMEWORK_PRIORITY")
        if v:
            return _split(v)
        v = self.get("filter", "framework_priority")
        if v:
            return _split(v)
        return list(DEFAULT_PRIORITY)

    # -- aliases ---------------------------------------------------------------
    def filter_aliases(self) -> Dict[str, str]:
        """(≙ [filter-aliases] section)"""
        out: Dict[str, str] = {}
        if self._ini.has_section("filter-aliases"):
            out.update({k: v for k, v in self._ini.items("filter-aliases")})
        v = self._env("NNS_TPU_FILTER_ALIASES")
        if v:
            out.update(_parse_pairs(v))
        return out

    # -- search paths ------------------------------------------------------------
    def custom_filter_paths(self) -> List[str]:
        """Directories searched for custom-filter .so files given a bare
        model name (≙ [filter] customfilters + NNSTREAMER_CUSTOMFILTERS)."""
        paths = _split(self.get("filter", "customfilters"))
        v = self._env("NNS_TPU_CUSTOMFILTERS")
        if v:
            paths = _split(v) + paths  # env first, like the reference
        return paths

    def resolve_custom_filter(self, model: str) -> str:
        """Return a full path for ``model``: absolute/existing paths pass
        through; bare names are searched in the configured directories."""
        if os.path.isfile(model):
            return model
        base = model if model.endswith(".so") else model + ".so"
        for d in self.custom_filter_paths():
            cand = os.path.join(d, base)
            if os.path.isfile(cand):
                return cand
        return model

    # -- element restriction ---------------------------------------------------
    def element_allowed(self, name: str) -> bool:
        """Product allowlisting (≙ enable_element_restriction +
        restricted_elements, meson_options.txt:52-53 / ini section). When
        restriction is on, only listed elements may be instantiated."""
        allow = self._env("NNS_TPU_RESTRICTED_ELEMENTS")
        if allow is None:
            if not self._getbool("elements", "enable_element_restriction",
                                 default=False):
                return True
            allow = self.get("elements", "restricted_elements")
        allowed = _split(allow)
        return not allowed or name in allowed


# module-level singleton, reloadable (tests call conf.reload())
conf = Conf()
