"""Pipeline tracing: proctime / interlatency / framerate per element.

≙ the GstShark tracers the reference leans on (tools/tracing/README.md:
proctime, interlatency, framerate, queue-level) — but built in, since
this runtime owns its scheduler. Enable per pipeline::

    tracer = pipeline.enable_tracing()
    pipeline.run()
    print(tracer.report())

Semantics:
  * proctime      — time spent inside each element's chain (already
                    accumulated in Element.stats; surfaced here)
  * interlatency  — time from a buffer's FIRST entry into the pipeline
                    to its arrival at each element (birth stamped in
                    buffer extras; copies inherit it via copy_meta_from)
  * framerate     — buffers/sec observed at each element
  * queue-level   — live fill of each queue element at report time
  * percentiles   — p50/p95/p99 of each series from a bounded
                    reservoir (O(1) per buffer, fixed memory), so tail
                    latency — the number a serving stack is judged on —
                    is observable beyond mean/peak
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Sequence

# bounded per-series sample budget: 512 f64 samples = 4 KB per element,
# enough for +/- a few percent on p99 at streaming rates
_RESERVOIR_K = 512


def _wire_summary(st: Dict[str, Any]) -> Dict[str, Any]:
    """Condense an element's wire_* counters (edge/wire.py) into the
    per-link block report() exposes; {} when the element never touched
    a socket, so non-networked elements stay uncluttered."""
    out: Dict[str, Any] = {}
    for key in ("wire_bytes_out", "wire_bytes_in",
                "wire_msgs_out", "wire_msgs_in"):
        if st.get(key):
            out[key[5:]] = st[key]
    raw, enc = st.get("wire_raw_bytes_out", 0), st.get("wire_enc_bytes_out", 0)
    if raw and enc:
        out["compress_ratio"] = round(raw / enc, 3)
    frames_out = st.get("wire_frames_out", 0)
    if frames_out:
        out["frames_out"] = frames_out
        out["pack_us_avg"] = round(
            st.get("wire_pack_ns", 0) / frames_out / 1e3, 2)
        msgs = st.get("wire_msgs_out", 0)
        if msgs:
            out["frames_per_msg"] = round(frames_out / msgs, 2)
    if st.get("wire_frames_in"):
        out["frames_in"] = st["wire_frames_in"]
    if st.get("wire_delta_keyframes") or st.get("wire_delta_diffs"):
        # delta codec sender: how much temporal redundancy the link shed
        out["delta"] = {
            "keyframes": st.get("wire_delta_keyframes", 0),
            "diffs": st.get("wire_delta_diffs", 0),
            "promotions": st.get("wire_delta_promotions", 0),
            "bytes_saved": st.get("wire_delta_bytes_saved", 0)}
    if st.get("wire_delta_keyframes_in") or st.get("wire_delta_diffs_in"):
        out["delta_in"] = {
            "keyframes": st.get("wire_delta_keyframes_in", 0),
            "diffs": st.get("wire_delta_diffs_in", 0)}
    return out


def _session_summary(st: Dict[str, Any], el=None) -> Dict[str, Any]:
    """Condense an element's session_* counters (edge/session.py) into
    the per-link delivery-guarantee block: sent/delivered, replays,
    dup-drops, DECLARED losses, ack traffic, heartbeat RTT. {} for
    sessionless elements so existing reports are unchanged. The numbers
    are exact by construction — the chaos harness asserts
    sent == delivered + declared_lost (+ in-flight) from this block."""
    out: Dict[str, Any] = {}
    for key, val in st.items():
        if key.startswith("session_") and val:
            out[key[8:]] = val
    pongs = st.get("session_pongs", 0)
    if pongs:
        out["rtt_us_avg"] = round(
            st.get("session_rtt_ns", 0) / pongs / 1e3, 1)
        out.pop("rtt_ns", None)
    # live (non-counter) gauges: ring fill, attached sessions, frames
    # awaiting a correlated result — whatever the element exposes
    info = getattr(el, "session_info", None)
    if callable(info):
        try:
            out.update(info() or {})
        except Exception:  # noqa: BLE001 — reporting must never raise
            pass
    return out


class Reservoir:
    """Algorithm-R bounded reservoir: O(1) cost per observation, fixed
    memory, uniformly representative of the whole stream — the classic
    answer to "percentiles without keeping every sample". Seeded, so a
    rerun of the same stream reports the same numbers."""

    __slots__ = ("k", "n", "samples", "_rng")

    def __init__(self, k: int = _RESERVOIR_K, seed: int = 0):
        self.k = max(1, int(k))
        self.n = 0
        self.samples: list = []
        self._rng = random.Random(seed)

    def add(self, value: float) -> None:
        self.n += 1
        if len(self.samples) < self.k:
            self.samples.append(value)
        else:
            j = self._rng.randrange(self.n)
            if j < self.k:
                self.samples[j] = value

    def percentiles(self, qs: Sequence[int] = (50, 95, 99)) -> Dict[str, float]:
        s = sorted(self.samples)
        out: Dict[str, float] = {}
        for q in qs:
            if not s:
                out[f"p{q}"] = 0.0
            else:
                out[f"p{q}"] = s[min(len(s) - 1,
                                     int(round(q / 100.0 * (len(s) - 1))))]
        return out


class WindowReservoir:
    """Time-windowed percentiles: samples older than ``window_s`` fall
    out. An all-stream reservoir is right for post-hoc tail reporting
    but wrong as a *control signal* — a burst's 300ms queue delays
    would linger in it long after the backlog drained, so an autoscaler
    reading p95 would never see recovery and never scale down. Bounded
    at ``k`` samples (newest win) so a burst can't grow memory."""

    __slots__ = ("window_s", "k", "n", "_buf")

    def __init__(self, window_s: float = 2.0, k: int = _RESERVOIR_K):
        self.window_s = max(1e-3, float(window_s))
        self.k = max(1, int(k))
        self.n = 0
        self._buf: deque = deque()  # (t_mono, value), oldest first

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        buf = self._buf
        while buf and (buf[0][0] < horizon or len(buf) > self.k):
            buf.popleft()

    def add(self, value: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.n += 1
        self._buf.append((now, value))
        self._prune(now)

    def samples(self, now: Optional[float] = None) -> list:
        self._prune(time.monotonic() if now is None else now)
        return [v for _, v in self._buf]

    def percentiles(self, qs: Sequence[int] = (50, 95, 99),
                    now: Optional[float] = None) -> Dict[str, float]:
        s = sorted(self.samples(now))
        out: Dict[str, float] = {}
        for q in qs:
            if not s:
                out[f"p{q}"] = 0.0
            else:
                out[f"p{q}"] = s[min(len(s) - 1,
                                     int(round(q / 100.0 * (len(s) - 1))))]
        return out


class _Agg:
    """O(1)-memory running aggregate (sum/max/count/first/last) plus a
    bounded reservoir for tail percentiles."""

    __slots__ = ("n", "total", "peak", "first_ts", "last_ts", "res")

    def __init__(self, now: float):
        self.n = 0
        self.total = 0
        self.peak = 0
        self.first_ts = now
        self.last_ts = now
        self.res = Reservoir()


class Tracer:
    BIRTH_KEY = "_trace_birth_ns"

    def __init__(self):
        # per-element aggregates; the lock keeps fan-in elements (mux
        # fed from several queue threads) from losing counts
        self._agg: Dict[str, _Agg] = {}
        self._lock = threading.Lock()
        # last-seen birth per streaming thread: elements that build a
        # FRESH Buffer (converter, mux, aggregator, decoders) drop the
        # extras, but their output is pushed synchronously inside the
        # chain of the buffer that caused it — so the thread's current
        # birth is the right inheritance. Sources stamp their buffers
        # explicitly (stamp()), so a root buffer never inherits a
        # predecessor's birth.
        self._tls = threading.local()

    def stamp(self, buf) -> None:
        """Mark a buffer's birth at the source (SrcElement/appsrc)."""
        buf.extras[self.BIRTH_KEY] = time.perf_counter_ns()

    # called from Element.chain for every buffer when tracing is on
    def record(self, element, buf) -> None:
        now_ns = time.perf_counter_ns()
        birth = buf.extras.get(self.BIRTH_KEY)
        if birth is None:
            birth = getattr(self._tls, "birth", None)
            if birth is None:
                birth = now_ns
            buf.extras[self.BIRTH_KEY] = birth
        self._tls.birth = birth
        self._observe(element.name, now_ns - birth, now_ns / 1e9)

    def observe(self, series: str, value_ns: float) -> None:
        """Feed a named scalar series (ns) from outside the buffer path —
        e.g. the serve scheduler's per-request queue delay and per-batch
        latency. Reported alongside elements with the same field names
        (the ``interlatency_us_*`` columns carry the observed value)."""
        self._observe(series, value_ns, time.perf_counter_ns() / 1e9)

    def _observe(self, key: str, lat: float, now: float) -> None:
        with self._lock:
            agg = self._agg.get(key)
            if agg is None:
                agg = self._agg[key] = _Agg(now)
            agg.n += 1
            agg.total += lat
            if lat > agg.peak:
                agg.peak = lat
            agg.res.add(lat)
            agg.last_ts = now

    def report(self, pipeline=None) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            snap = {k: (a.n, a.total, a.peak, a.first_ts, a.last_ts,
                        a.res.percentiles())
                    for k, a in self._agg.items()}
        for name, (n, total, peak, first_ts, last_ts, pct) in snap.items():
            dt = last_ts - first_ts
            out[name] = {
                "buffers": n,
                "interlatency_us_avg": total / n / 1e3 if n else 0.0,
                "interlatency_us_max": peak / 1e3,
                "interlatency_us_p50": pct["p50"] / 1e3,
                "interlatency_us_p95": pct["p95"] / 1e3,
                "interlatency_us_p99": pct["p99"] / 1e3,
                "framerate_fps": (n - 1) / dt if n > 1 and dt > 0 else 0.0,
            }
        if pipeline is not None:
            for name, el in pipeline.elements.items():
                entry = out.setdefault(name, {})
                # one consistent point-in-time copy per element: a
                # mid-flight chain bump can't tear buffers/proctime
                st = el.stats.snapshot()
                if st.get("buffers"):
                    entry["proctime_us_avg"] = (st["proctime_ns"] /
                                                st["buffers"] / 1e3)
                # fault accounting: only shown when something actually
                # happened, so healthy reports stay uncluttered
                for key in ("dropped", "retries", "restarts", "shed"):
                    if st.get(key):
                        entry[key] = st[key]
                w = _wire_summary(st)
                if w:
                    entry["wire"] = w
                s = _session_summary(st, el)
                if s:
                    entry["session"] = s
                q = getattr(el, "_q", None)
                if q is not None and hasattr(q, "qsize"):
                    entry["queue_level"] = q.qsize()
            for name, el in pipeline.elements.items():
                rep = getattr(el, "router_report", None)
                if callable(rep):
                    r = rep()
                    if r:
                        out.setdefault(name, {})["router"] = r
            fusion = self._fusion_block(pipeline, out)
            if fusion:
                out["fusion"] = fusion
            transfer = self._transfer_block(pipeline)
            if transfer:
                out["transfer"] = transfer
        # control-plane counters: any live in-process discovery broker
        # (register/query/error totals) surfaces next to the elements
        try:
            from ..edge.broker import live_broker_stats
            b = live_broker_stats()
            if b:
                out["broker"] = b
        except Exception:  # noqa: BLE001 — observability must not raise
            pass
        return out

    @staticmethod
    def _fusion_block(pipeline, report: Dict[str, Dict[str, Any]]
                      ) -> Dict[str, Any]:
        """Aggregate fusion-compiler stats: one sub-entry per
        FusedSegment (member count, jit cache hits/misses, p50 of the
        device-program dispatch latency observed as ``fusion/<name>``)
        plus pipeline totals. {} on unfused pipelines so existing
        reports are unchanged."""
        segments: Dict[str, Any] = {}
        for name, el in pipeline.elements.items():
            if not getattr(el, "IS_FUSED_SEGMENT", False):
                continue
            st = el.stats.snapshot()
            seg = {
                "elements": st.get("fused_elements", 0),
                "members": [m.name for m in getattr(el, "members", [])],
                "jit_hits": st.get("jit_hits", 0),
                "jit_misses": st.get("jit_misses", 0),
                # chips one dispatch of this segment's program spans:
                # the hit/miss and dispatch-latency numbers are
                # per-PROGRAM (per-mesh), not per-chip — a sharded
                # batch is one dispatch, so dividing by devices would
                # undercount
                "devices": st.get("devices", 1) or 1,
            }
            # the dispatch-latency series is internal plumbing; fold it
            # into the segment entry instead of a top-level row
            series = report.pop(f"fusion/{name}", None)
            if series is not None:
                seg["dispatch_us_p50"] = series["interlatency_us_p50"]
                seg["dispatch_us_p95"] = series["interlatency_us_p95"]
            segments[name] = seg
        if not segments:
            return {}
        return {
            "segments": len(segments),
            "fused_elements": sum(s["elements"] for s in segments.values()),
            "jit_hits": sum(s["jit_hits"] for s in segments.values()),
            "jit_misses": sum(s["jit_misses"] for s in segments.values()),
            "devices": max(s["devices"] for s in segments.values()),
            "per_segment": segments,
        }

    @staticmethod
    def _transfer_block(pipeline) -> Dict[str, Any]:
        """The overlapped-execution view: per-element in-flight window
        stats (occupancy, overlap ratio — from each element's
        ``transfer_report()``) plus the bidirectional coalescing
        service's achieved depths (upload/download frames-per-RPC).
        {} when nothing overlapped or coalesced, so existing reports
        are unchanged."""
        out: Dict[str, Any] = {}
        windows: Dict[str, Any] = {}
        for name, el in pipeline.elements.items():
            rep = getattr(el, "transfer_report", None)
            if callable(rep):
                try:
                    r = rep()
                except Exception:  # noqa: BLE001 — reporting never raises
                    continue
                if r:
                    windows[name] = r
        if windows:
            out["windows"] = windows
            ratios = [w["overlap_ratio"] for w in windows.values()
                      if w.get("overlap_ratio")]
            if ratios:
                out["overlap_ratio"] = round(max(ratios), 2)
            # window stats are per-MESH: a sharded in-flight frame is
            # one slot across every chip its program spans, so the
            # occupancy/blocked numbers must not be read per-chip —
            # surface the widest span so the block is self-describing.
            # Always present (1 = per-chip), matching the fusion block.
            spans = [int(w.get("devices", 1) or 1)
                     for w in windows.values()]
            out["devices"] = max(spans) if spans else 1
        try:
            from ..tensors.transfer import transfer_stats
            svc = transfer_stats()
            for direction, st in svc.items():
                if st.get("rpcs"):
                    out[direction] = {
                        "rpcs": st["rpcs"], "frames": st["frames"],
                        "arrays": st["arrays"],
                        "coalesce_avg": round(st["frames_per_rpc_avg"], 2),
                    }
        except Exception:  # noqa: BLE001 — observability must not raise
            pass
        return out
