"""TensorInfo / TensorsInfo / TensorsConfig.

Semantic equivalent of GstTensorInfo/GstTensorsInfo/GstTensorsConfig and the
dimension-string grammar of the reference
(ref: gst/nnstreamer/nnstreamer_plugin_api_util_impl.c — parse/compare/copy
dimension helpers; tensor_typedef.h:273-289 struct layout).

Dimension strings are reference-compatible: ``"3:224:224"`` is
innermost-first (channel:width:height for NHWC video). Internally we keep
NumPy/JAX order (outermost-first), i.e. that string parses to shape
``(224, 224, 3)``. Trailing ``:1`` padding is accepted and **stripped** on
parse (the reference pads ranks with 1s, so ``"3:224:224:1"`` equals
``"3:224:224"`` and also parses to ``(224, 224, 3)``);
``dim_string()`` emits the minimal form.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Iterable, Optional, Sequence, Tuple

from .types import RANK_LIMIT, TENSOR_COUNT_LIMIT, TensorFormat, TensorType


def parse_dimension(dim_str: str) -> Tuple[int, ...]:
    """Parse a reference-style dimension string into a NumPy-order shape.

    ``"3:224:224:2"`` -> ``(2, 224, 224, 3)``; trailing 1s are rank padding
    and are stripped, so ``"3:224:224:1"`` -> ``(224, 224, 3)`` (the
    reference pads ranks with 1s, nnstreamer_plugin_api_util_impl.c
    dimension parsing). ``0`` terminates the dimension (unspecified
    remainder), matching the reference.
    """
    dim_str = dim_str.strip()
    if not dim_str:
        return ()
    parts = dim_str.split(":")
    if len(parts) > RANK_LIMIT:
        raise ValueError(f"rank {len(parts)} exceeds limit {RANK_LIMIT}")
    dims = []
    for p in parts:
        v = int(p)
        if v == 0:
            break  # 0 terminates: remainder unspecified
        if v < 0:
            raise ValueError(f"negative dimension in {dim_str!r}")
        dims.append(v)
    # strip trailing 1-padding (innermost-first order: padding is at the end)
    while len(dims) > 1 and dims[-1] == 1:
        dims.pop()
    return tuple(reversed(dims))


def serialize_dimension(shape: Sequence[int], rank: Optional[int] = None) -> str:
    """NumPy-order shape -> reference-style innermost-first string.

    ``(1, 224, 224, 3)`` -> ``"3:224:224:1"``. If ``rank`` is given, pad
    with 1s up to that rank.
    """
    dims = list(reversed([int(d) for d in shape]))
    if not dims:
        dims = [1]
    if rank is not None:
        if rank < len(dims):
            raise ValueError(f"rank {rank} < len(shape) {len(dims)}")
        dims += [1] * (rank - len(dims))
    return ":".join(str(d) for d in dims)


@dataclasses.dataclass
class TensorInfo:
    """One tensor's name, element type, and shape (ref: GstTensorInfo)."""

    name: Optional[str] = None
    type: Optional[TensorType] = None
    shape: Tuple[int, ...] = ()

    @classmethod
    def make(cls, type: "TensorType | str", dim: "str | Sequence[int]",
             name: Optional[str] = None) -> "TensorInfo":
        if isinstance(type, str):
            type = TensorType.from_string(type)
        shape = parse_dimension(dim) if isinstance(dim, str) else tuple(int(d) for d in dim)
        return cls(name=name, type=type, shape=shape)

    @property
    def num_elements(self) -> int:
        return math.prod(self.shape) if self.shape else 0

    @property
    def size_bytes(self) -> int:
        if self.type is None:
            return 0
        return self.num_elements * self.type.element_size

    def is_valid(self) -> bool:
        return (
            self.type is not None
            and len(self.shape) >= 1
            and all(d > 0 for d in self.shape)
        )

    def dim_string(self, rank: Optional[int] = None) -> str:
        return serialize_dimension(self.shape, rank)

    def is_equal(self, other: "TensorInfo") -> bool:
        """Type+shape equality, ignoring names and size-1 padding dims
        (ref: gst_tensor_info_is_equal — dims compare padded with 1s to
        rank 16; in numpy order the padding 1s are leading)."""

        def norm(shape: Tuple[int, ...]) -> Tuple[int, ...]:
            s = tuple(shape)
            while len(s) > 1 and s[0] == 1:
                s = s[1:]
            return s

        return self.type == other.type and \
            norm(self.shape) == norm(other.shape)

    def copy(self) -> "TensorInfo":
        return TensorInfo(self.name, self.type, tuple(self.shape))

    def __str__(self) -> str:
        t = str(self.type) if self.type is not None else "?"
        return f"{self.name or ''}[{t}:{self.dim_string()}]"


class TensorsInfo:
    """Ordered collection of TensorInfo (ref: GstTensorsInfo)."""

    def __init__(self, infos: Iterable[TensorInfo] = ()):  # noqa: D107
        self._infos = list(infos)
        if len(self._infos) > TENSOR_COUNT_LIMIT:
            raise ValueError(
                f"{len(self._infos)} tensors exceeds limit {TENSOR_COUNT_LIMIT}")

    @classmethod
    def make(cls, types: "str | Sequence", dims: "str | Sequence",
             names: Optional[Sequence[Optional[str]]] = None) -> "TensorsInfo":
        """Build from property-style strings: types="uint8,float32",
        dims="3:224:224,10:1" (ref: property parsing in tensor_filter_common.c).
        """
        if isinstance(types, str):
            types = [t for t in types.split(",") if t.strip()]
        if isinstance(dims, str):
            dims = [d for d in dims.split(",") if d.strip()]
        if len(types) != len(dims):
            raise ValueError("types/dims count mismatch")
        names = names or [None] * len(types)
        return cls(
            TensorInfo.make(t, d, n) for t, d, n in zip(types, dims, names))

    def __len__(self) -> int:
        return len(self._infos)

    def __getitem__(self, i: int) -> TensorInfo:
        return self._infos[i]

    def __iter__(self):
        return iter(self._infos)

    def append(self, info: TensorInfo) -> None:
        if len(self._infos) >= TENSOR_COUNT_LIMIT:
            raise ValueError("tensor count limit exceeded")
        self._infos.append(info)

    def is_valid(self) -> bool:
        return len(self._infos) > 0 and all(i.is_valid() for i in self._infos)

    def is_equal(self, other: "TensorsInfo") -> bool:
        return len(self) == len(other) and all(
            a.is_equal(b) for a, b in zip(self, other))

    def total_size_bytes(self) -> int:
        return sum(i.size_bytes for i in self._infos)

    def copy(self) -> "TensorsInfo":
        return TensorsInfo(i.copy() for i in self._infos)

    def types_string(self) -> str:
        return ",".join(str(i.type) for i in self._infos)

    def dims_string(self, rank: Optional[int] = None) -> str:
        return ",".join(i.dim_string(rank) for i in self._infos)

    def names_string(self) -> str:
        return ",".join(i.name or "" for i in self._infos)

    def __repr__(self) -> str:
        return f"TensorsInfo({', '.join(str(i) for i in self._infos)})"


@dataclasses.dataclass
class TensorsConfig:
    """Stream configuration: infos + format + framerate
    (ref: GstTensorsConfig, tensor_typedef.h:284-289)."""

    info: TensorsInfo = dataclasses.field(default_factory=TensorsInfo)
    format: TensorFormat = TensorFormat.STATIC
    rate_n: int = 0   # framerate numerator; 0/1 = unknown-rate stream
    rate_d: int = 1

    def is_valid(self) -> bool:
        if self.rate_d <= 0 or self.rate_n < 0:
            return False
        if self.format == TensorFormat.STATIC:
            return self.info.is_valid()
        return True  # flexible/sparse: per-buffer meta carries shape

    def is_equal(self, other: "TensorsConfig") -> bool:
        if self.format != other.format:
            return False
        if (self.rate_n * other.rate_d) != (other.rate_n * self.rate_d):
            return False
        if self.format == TensorFormat.STATIC:
            return self.info.is_equal(other.info)
        return True

    def copy(self) -> "TensorsConfig":
        return TensorsConfig(self.info.copy(), self.format, self.rate_n, self.rate_d)

    @property
    def framerate(self) -> float:
        return self.rate_n / self.rate_d if self.rate_d else 0.0

    def frame_duration_ns(self) -> Optional[int]:
        if self.rate_n <= 0:
            return None
        return int(round(1e9 * self.rate_d / self.rate_n))
