"""Tensor element types, stream formats, and layouts.

TPU-native re-design of the reference type system
(ref: gst/nnstreamer/include/tensor_typedef.h:138-226).

Differences from the reference, by design:
  * ``BFLOAT16`` is added (TPU-native compute dtype; the MXU wants bf16).
  * Shapes are stored in NumPy/JAX order (outermost-first). The reference's
    dimension *strings* ("3:224:224:1", innermost-first) are parsed/emitted
    compatibly by :mod:`nnstreamer_tpu.tensors.info`.
  * No 16-memory-chunk packing limit: buffers hold a Python list of chunks
    (ref's NNS_TENSOR_MEMORY_MAX/extra-magic hack in
    nnstreamer_plugin_api_impl.c:54-91 is a GstBuffer limitation we don't have).
"""
from __future__ import annotations

import enum

import numpy as np

try:  # jax optional at import time so the tensor core stays host-usable
    import jax.numpy as jnp

    _HAS_JAX = True
except Exception:  # pragma: no cover
    jnp = None
    _HAS_JAX = False

# Rank limit matches the reference (tensor_typedef.h:34).
RANK_LIMIT = 16
# Max tensors per frame (tensor_typedef.h:42); ours is a soft cap for caps
# validation only -- buffers are plain lists.
TENSOR_COUNT_LIMIT = 256


class TensorType(enum.IntEnum):
    """Element dtype of one tensor (ref: tensor_typedef.h:141-153).

    Integer values match the reference enum so serialized streams and
    protobuf/flatbuf schemas stay interoperable. BFLOAT16 is appended after
    the reference's last value.
    """

    INT32 = 0
    UINT32 = 1
    INT16 = 2
    UINT16 = 3
    INT8 = 4
    UINT8 = 5
    FLOAT64 = 6
    FLOAT32 = 7
    INT64 = 8
    UINT64 = 9
    FLOAT16 = 10
    BFLOAT16 = 11  # TPU-native extension

    @property
    def np_dtype(self) -> np.dtype:
        return _NP_DTYPES[self]

    @property
    def element_size(self) -> int:
        return _ELEMENT_SIZES[self]

    def __str__(self) -> str:  # caps-string form
        return _TYPE_NAMES[self]

    @classmethod
    def from_string(cls, name: str) -> "TensorType":
        try:
            return _TYPE_BY_NAME[name.strip().lower()]
        except KeyError:
            raise ValueError(f"unknown tensor type {name!r}") from None

    @classmethod
    def from_dtype(cls, dtype) -> "TensorType":
        name = np.dtype(dtype).name if str(dtype) != "bfloat16" else "bfloat16"
        if str(dtype) == "bfloat16":
            return cls.BFLOAT16
        try:
            return _TYPE_BY_NAME[name]
        except KeyError:
            raise ValueError(f"unsupported dtype {dtype!r}") from None


_TYPE_NAMES = {
    TensorType.INT32: "int32",
    TensorType.UINT32: "uint32",
    TensorType.INT16: "int16",
    TensorType.UINT16: "uint16",
    TensorType.INT8: "int8",
    TensorType.UINT8: "uint8",
    TensorType.FLOAT64: "float64",
    TensorType.FLOAT32: "float32",
    TensorType.INT64: "int64",
    TensorType.UINT64: "uint64",
    TensorType.FLOAT16: "float16",
    TensorType.BFLOAT16: "bfloat16",
}
_TYPE_BY_NAME = {v: k for k, v in _TYPE_NAMES.items()}


def _bf16_np_dtype():
    if _HAS_JAX:
        return jnp.bfloat16
    try:  # pragma: no cover
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    except Exception:  # pragma: no cover
        raise RuntimeError("bfloat16 requires jax or ml_dtypes")


class _LazyDtypes(dict):
    """bfloat16 resolves lazily so a jax-less host falls back to ml_dtypes
    (or raises) instead of silently yielding None."""

    def __missing__(self, key):
        if key is TensorType.BFLOAT16:
            dt = np.dtype(_bf16_np_dtype())
            self[key] = dt
            return dt
        raise KeyError(key)


_NP_DTYPES = _LazyDtypes({
    t: np.dtype(_TYPE_NAMES[t])
    for t in TensorType
    if t is not TensorType.BFLOAT16
})

_ELEMENT_SIZES = {
    TensorType.INT32: 4,
    TensorType.UINT32: 4,
    TensorType.INT16: 2,
    TensorType.UINT16: 2,
    TensorType.INT8: 1,
    TensorType.UINT8: 1,
    TensorType.FLOAT64: 8,
    TensorType.FLOAT32: 4,
    TensorType.INT64: 8,
    TensorType.UINT64: 8,
    TensorType.FLOAT16: 2,
    TensorType.BFLOAT16: 2,
}


class TensorFormat(enum.IntEnum):
    """Stream data format (ref: tensor_typedef.h:193-200)."""

    STATIC = 0
    FLEXIBLE = 1
    SPARSE = 2

    def __str__(self) -> str:
        return self.name.lower()

    @classmethod
    def from_string(cls, name: str) -> "TensorFormat":
        try:
            return cls[name.strip().upper()]
        except KeyError:
            raise ValueError(f"unknown tensor format {name!r}") from None


class TensorLayout(enum.IntEnum):
    """Memory layout hint (ref: tensor_typedef.h:220-226)."""

    ANY = 0
    NHWC = 1
    NCHW = 2
    NONE = 3


class MediaType(enum.IntEnum):
    """Input media types for conversion (ref: tensor_typedef.h:176-187)."""

    INVALID = -1
    VIDEO = 0
    AUDIO = 1
    TEXT = 2
    OCTET = 3
    TENSOR = 4
    ANY = 0x1000


# Mimetype string for caps (ref: tensor_typedef.h:97).
MIMETYPE_TENSORS = "other/tensors"
