"""Tensor type system (L1): dtypes, shapes, caps, frames, meta headers."""
from .buffer import Buffer, BufferFlags, Chunk
from .caps import AltSet, Caps, CapsStructure, FractionRange, IntRange
from .info import (TensorInfo, TensorsConfig, TensorsInfo, parse_dimension,
                   serialize_dimension)
from .meta import HEADER_SIZE, TensorMetaInfo
from .types import (MIMETYPE_TENSORS, RANK_LIMIT, TENSOR_COUNT_LIMIT,
                    MediaType, TensorFormat, TensorLayout, TensorType)

__all__ = [
    "Buffer", "BufferFlags", "Chunk", "Caps", "CapsStructure", "AltSet",
    "IntRange", "FractionRange", "TensorInfo", "TensorsInfo", "TensorsConfig",
    "parse_dimension", "serialize_dimension", "TensorMetaInfo", "HEADER_SIZE",
    "TensorType", "TensorFormat", "TensorLayout", "MediaType",
    "MIMETYPE_TENSORS", "RANK_LIMIT", "TENSOR_COUNT_LIMIT",
]
