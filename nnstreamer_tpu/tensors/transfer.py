"""Bidirectional coalescing host<->device transfer service.

Generalizes the one-way D2H fetch coalescer (this module's ancestor
lived in ``tensors/fetch.py``, which now re-exports from here) into the
transfer layer the async overlapped executor sits on:

  * **download** — the original coalescing D2H fetcher: frames enqueue
    their outputs with :func:`submit_fetch` and leave immediately
    carrying :class:`PendingHost` handles; one fetcher thread drains
    everything queued into one batched ``jax.device_get`` per RPC.
  * **upload** — the symmetric H2D side: :func:`submit_upload` enqueues
    host arrays for a device and returns :class:`PendingDevice`
    handles; one uploader thread drains everything queued into one
    batched ``jax.device_put`` per RPC (grouped per target device), so
    the H2D legs of consecutive in-flight frames share round trips —
    the "double-buffered H2D" leg of the overlap window.
  * **in-flight window** — :class:`InFlightWindow`, the per-link bound
    on frames between dispatch and completion. ``acquire`` blocks the
    dispatching chain thread when the window is full, which is exactly
    the backpressure the upstream ``queue`` element needs to see.

Why coalescing (both directions): on a tunneled dev chip every transfer
RPC costs a full link round trip (measured 10-100 ms depending on link
weather, regardless of payload size). Batching N frames' arrays into
one RPC amortizes that round trip N ways; the adaptive Nagle-style
linger below lets stragglers join without ever delaying a lone frame by
more than 5% of the measured RPC time.

``transfer_stats()`` reports both directions; ``fetch_stats()`` keeps
the historical download-only contract. ``trace.report()`` surfaces the
same numbers in its ``transfer`` block together with each element's
window occupancy and overlap ratio.

The reference has no analog (host pointers are free there); this is the
TPU-native cost model talking (SURVEY.md §7 hard part (b): device
residency, materialize only at host boundaries — here even the
materialization is pipelined and batched, in both directions).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import flowmarks as flow

# cap on arrays per RPC so one giant drain can't add unbounded latency
# to the frames queued behind it
_MAX_ARRAYS_PER_RPC = 256

# test/bench hook: added per-RPC latency (seconds) simulating link
# weather. Applied inside the transfer threads only — never on a chain
# thread — so it models the link, not the host. 0.0 = off.
_sim_rtt_s = 0.0


def set_simulated_rtt_ms(ms: float) -> None:
    """Inject ``ms`` of artificial round-trip latency into every
    transfer RPC (both directions). Bench/test knob for reproducing
    link weather on a local backend; production leaves it at 0."""
    global _sim_rtt_s
    _sim_rtt_s = max(0.0, float(ms)) / 1e3


class _Ticket:
    """One frame's transfer: a list of arrays -> their counterparts on
    the other side of the link."""

    __slots__ = ("arrays", "results", "error", "device", "_evt")

    def __init__(self, arrays: List[Any], device: Any = None):
        self.arrays: Optional[List[Any]] = arrays
        self.results: Optional[List[Any]] = None
        self.error: Optional[BaseException] = None
        self.device = device           # upload target; None for download
        self._evt = threading.Event()

    @property
    def done(self) -> bool:
        return self._evt.is_set()

    def _deliver(self, results: Optional[List[Any]],
                 error: Optional[BaseException] = None) -> None:
        self.results = results
        self.error = error
        self.arrays = None  # the transfer thread's refs go; buffer
        self._evt.set()     # lifetime is now governed by the handles

    def wait(self) -> List[Any]:
        self._evt.wait()
        if self.error is not None:
            raise self.error
        assert self.results is not None
        return self.results


class _Coalescer:
    """One direction of the link: a queue of tickets drained by a
    single daemon thread, one batched RPC per drain. Subclasses name
    the thread and provide :meth:`_rpc`."""

    THREAD_NAME = "nns-transfer"

    def __init__(self):
        self._q: List[_Ticket] = []
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        # achieved-depth accounting: frames (tickets) per RPC is THE
        # number that says whether the service actually amortizes the
        # link round trip (1.0 = degenerated to frame-at-a-time)
        self._stats = {"rpcs": 0, "frames": 0, "arrays": 0}

    # direction-specific batched transfer; raises to trigger the
    # per-ticket retry isolation in _run
    def _rpc(self, tickets: List[_Ticket], flat: List[Any]) -> List[Any]:
        raise NotImplementedError

    def stats(self, reset: bool = False) -> dict:
        with self._cv:
            out = dict(self._stats)
            if reset:
                self._stats.update(rpcs=0, frames=0, arrays=0)
        out["frames_per_rpc_avg"] = (
            out["frames"] / out["rpcs"] if out["rpcs"] else 0.0)
        return out

    def _account(self, n_tickets: int, n_arrays: int) -> None:
        with self._cv:
            self._stats["rpcs"] += 1
            self._stats["frames"] += n_tickets
            self._stats["arrays"] += n_arrays

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name=self.THREAD_NAME, daemon=True)
            self._thread.start()

    def submit(self, ticket: _Ticket) -> None:
        with self._cv:
            self._ensure_thread()
            self._q.append(ticket)
            self._cv.notify()

    def _grab_batch(self) -> List[_Ticket]:
        """Pop a device-uniform run of tickets up to the per-RPC array
        cap. Mixed target devices can't share one RPC: the run stops at
        the first ticket bound elsewhere (it leads the next drain)."""
        grab: List[_Ticket] = []
        n = 0
        with self._cv:
            while self._q and n < _MAX_ARRAYS_PER_RPC:
                if grab and self._q[0].device is not grab[0].device:
                    break
                t = self._q.pop(0)
                grab.append(t)
                n += len(t.arrays or ())
        return grab

    def _run(self) -> None:
        import time as _time

        last_rpc = 0.0
        while True:
            with self._cv:
                while not self._q:
                    self._cv.wait()
            # adaptive linger (Nagle-style): on a slow link, draining the
            # instant the first ticket lands races the pipeline's refill
            # — the consumer frees queue slots only when THIS delivery
            # runs, so tickets submitted a millisecond after the drain
            # wait a whole extra round trip. A pause of 5% of the last
            # RPC (capped 4 ms) lets stragglers join. The worst case is
            # bounded by construction: the pause never exceeds 5% of the
            # measured RPC time, so even a fast link moving big payloads
            # pays <=5% slower cadence, repaid by any batching gain at
            # all; tiny-payload RPCs (the latency-sensitive case) have
            # tiny durations and skip the pause entirely. Measured:
            # ~1.7-1.9x devres pipeline fps at ~100 ms RTT, unchanged at
            # sub-ms RTT. Skipped when the backlog already fills an RPC
            # — waiting could not deepen that batch, only delay it.
            linger = min(0.004, last_rpc * 0.05)
            if linger > 0.0005:
                with self._cv:
                    backlog = sum(len(t.arrays or ()) for t in self._q)
                if backlog < _MAX_ARRAYS_PER_RPC:
                    _time.sleep(linger)
            grab = self._grab_batch()
            if not grab:
                continue
            flat = [a for t in grab for a in (t.arrays or ())]
            t0 = _time.perf_counter()
            try:
                if _sim_rtt_s > 0.0:
                    _time.sleep(_sim_rtt_s)
                results = self._rpc(grab, flat)
                last_rpc = _time.perf_counter() - t0
                self._account(len(grab), len(flat))
            except BaseException:  # noqa: BLE001 - isolate per frame below
                # one poisoned array (donated buffer, transient RPC error)
                # must not fail every frame sharing the RPC: retry each
                # ticket alone so only the genuinely bad frame errors out.
                # The failed round trip still cost a full RTT: count it
                # (0 frames delivered) so frames_per_rpc_avg cannot read
                # BETTER than reality on an unhealthy link; account each
                # retry before delivering so a resolve-then-reset caller
                # never sees counts land after its reset. The failed
                # attempt still measured real link time — keep the
                # linger's RPC estimate live through error storms.
                last_rpc = _time.perf_counter() - t0
                self._account(0, 0)
                for t in grab:
                    t1 = _time.perf_counter()
                    try:
                        res1 = self._rpc([t], list(t.arrays or []))
                        last_rpc = _time.perf_counter() - t1
                        self._account(1, len(t.arrays or ()))
                        t._deliver(res1)
                    except BaseException as exc:  # noqa: BLE001
                        self._account(0, 0)
                        t._deliver(None, exc)
                continue
            i = 0
            for t in grab:
                k = len(t.arrays or ())
                t._deliver(results[i:i + k])
                i += k


class _Downloader(_Coalescer):
    """D2H: one batched ``jax.device_get`` per RPC."""

    THREAD_NAME = "nns-fetch"

    def _rpc(self, tickets: List[_Ticket], flat: List[Any]) -> List[Any]:
        import jax
        return list(jax.device_get(flat))


class _Uploader(_Coalescer):
    """H2D: one batched ``jax.device_put`` per RPC. _grab_batch keeps
    each drain device-uniform, so the whole flat list ships in one
    call."""

    THREAD_NAME = "nns-upload"

    def _rpc(self, tickets: List[_Ticket], flat: List[Any]) -> List[Any]:
        import jax
        return list(jax.device_put(flat, tickets[0].device))


_downloader = _Downloader()
_uploader = _Uploader()


class PendingHost:
    """A device array whose host copy is in flight.

    Shape/dtype are known immediately (from the array's aval, no sync);
    :meth:`resolve` blocks until the coalescer's ``device_get`` lands.
    One ticket is shared by every output of a frame. ``dev`` keeps the
    device array reachable so device-side consumers stay in HBM without
    waiting; it is dropped at first resolution.
    """

    __slots__ = ("_ticket", "_index", "dev", "shape", "dtype")

    def __init__(self, ticket: _Ticket, index: int, dev):
        self._ticket = ticket
        self._index = index
        self.dev = dev
        self.shape = tuple(dev.shape)
        self.dtype = np.dtype(dev.dtype)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def done(self) -> bool:
        return self._ticket.done

    def resolve(self) -> np.ndarray:
        out = self._ticket.wait()[self._index]
        self.dev = None
        return out


class PendingDevice:
    """A host array whose device copy is in flight — the upload mirror
    of :class:`PendingHost`. ``host`` keeps the source array reachable
    until the upload lands; shape/dtype are free."""

    __slots__ = ("_ticket", "_index", "host", "shape", "dtype")

    def __init__(self, ticket: _Ticket, index: int, host):
        self._ticket = ticket
        self._index = index
        self.host = host
        self.shape = tuple(host.shape)
        self.dtype = np.dtype(host.dtype)

    @property
    def done(self) -> bool:
        return self._ticket.done

    def resolve(self) -> Any:
        out = self._ticket.wait()[self._index]
        self.host = None
        return out


def submit_fetch(outputs: Sequence[Any]) -> List[Any]:
    """Enqueue one coalesced fetch for all device-resident outputs of a
    frame; host arrays pass through untouched. Returns the outputs with
    device arrays replaced by :class:`PendingHost` handles."""
    import jax

    dev_idx = [i for i, o in enumerate(outputs)
               if isinstance(o, jax.Array)]
    if not dev_idx:
        return list(outputs)
    ticket = _Ticket([outputs[i] for i in dev_idx])
    _downloader.submit(ticket)
    wrapped = list(outputs)
    for slot, i in enumerate(dev_idx):
        wrapped[i] = PendingHost(ticket, slot, outputs[i])
    return wrapped


def submit_upload(inputs: Sequence[Any], device: Any) -> List[Any]:
    """Enqueue one coalesced upload of all host-resident inputs of a
    frame to ``device``; device arrays pass through untouched. Returns
    the inputs with host arrays replaced by :class:`PendingDevice`
    handles. Frames queued while an upload RPC is in flight share the
    next one — consecutive in-flight frames' H2D legs overlap."""
    import jax

    host_idx = [i for i, x in enumerate(inputs)
                if not isinstance(x, (jax.Array, PendingHost, PendingDevice))]
    if not host_idx:
        return list(inputs)
    ticket = _Ticket([np.asarray(inputs[i]) for i in host_idx],
                     device=device)
    _uploader.submit(ticket)
    wrapped = list(inputs)
    for slot, i in enumerate(host_idx):
        wrapped[i] = PendingDevice(ticket, slot, np.asarray(inputs[i]))
    return wrapped


def resolve(x: Any) -> Any:
    """Materialize ``x`` if it is a pending transfer; identity
    otherwise."""
    return x.resolve() if isinstance(x, (PendingHost, PendingDevice)) else x


def fetch_stats(reset: bool = False) -> dict:
    """Download-side counters: rpcs / frames / arrays since start (or
    last reset) plus ``frames_per_rpc_avg``, the achieved batching depth
    — the observability hook for "is the RTT actually being amortized".
    (Historical name; the upload mirror is in :func:`transfer_stats`.)"""
    return _downloader.stats(reset=reset)


def transfer_stats(reset: bool = False) -> Dict[str, dict]:
    """Both directions' coalescer counters, keyed ``download`` /
    ``upload`` — the service half of ``trace.report()``'s ``transfer``
    block (the per-element half is each window's report)."""
    return {"download": _downloader.stats(reset=reset),
            "upload": _uploader.stats(reset=reset)}


class InFlightWindow:
    """The per-link bound on frames between dispatch and completion.

    ``acquire`` blocks the dispatching chain thread while ``limit``
    frames are in flight — backpressure that propagates into the
    upstream queue element exactly like a slow synchronous invoke
    would, so bounded-queue flow control keeps working under overlap.
    ``release`` is called by the completer once the frame has been
    pushed downstream (or accounted dropped).

    The occupancy/overlap accounting lives here because the window IS
    the overlap: ``overlap_ratio`` is total in-flight frame-seconds
    over the dispatch-to-last-completion wall span — 1.0 means serial
    (no overlap won), ``limit`` means the window ran full depth.

    ``devices`` records how many chips one slot's dispatch spans: the
    budget is per-mesh, so a batch sharded across an 8-chip mesh still
    occupies exactly ONE slot (it is one XLA dispatch with one
    completion), not ``len(mesh.devices)`` — a window of K means K
    outstanding programs regardless of how wide each program is. The
    value is reporting-only; it never scales the limit.
    """

    def __init__(self, limit: int, devices: int = 1):
        self.limit = max(1, int(limit))
        self.devices = max(1, int(devices))
        self._cv = threading.Condition()
        self._inflight = 0
        self._peak = 0
        self._acquires = 0
        self._occupancy_sum = 0       # inflight depth sampled per acquire
        self._blocked_ns = 0
        self._inflight_ns = 0         # sum of per-frame dispatch->release
        self._first_ns: Optional[int] = None
        self._last_ns: Optional[int] = None

    @flow.acquires("window-slot")
    def acquire(self, timeout: Optional[float] = None) -> Optional[int]:
        """Take a window slot; returns the dispatch timestamp (ns) to
        hand back to :meth:`release`, or None on timeout."""
        import time as _time
        t0 = _time.perf_counter_ns()
        with self._cv:
            while self._inflight >= self.limit:
                if not self._cv.wait(timeout):
                    return None
            now = _time.perf_counter_ns()
            self._blocked_ns += now - t0
            self._inflight += 1
            self._acquires += 1
            self._occupancy_sum += self._inflight
            if self._inflight > self._peak:
                self._peak = self._inflight
            if self._first_ns is None:
                self._first_ns = now
            return now

    @flow.settles("window-slot")
    def release(self, t_dispatch_ns: int) -> None:
        import time as _time
        now = _time.perf_counter_ns()
        with self._cv:
            self._inflight -= 1
            self._inflight_ns += now - t_dispatch_ns
            self._last_ns = now
            self._cv.notify_all()

    def idle(self) -> bool:
        with self._cv:
            return self._inflight == 0

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        import time as _time
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                left = None if deadline is None \
                    else deadline - _time.monotonic()
                if left is not None and left <= 0:
                    return False
                if not self._cv.wait(left if left is not None else 1.0):
                    return False
            return True

    def report(self) -> Dict[str, Any]:
        with self._cv:
            span = ((self._last_ns - self._first_ns)
                    if self._first_ns is not None
                    and self._last_ns is not None else 0)
            return {
                "window": self.limit,
                "devices": self.devices,
                "in_flight": self._inflight,
                "in_flight_peak": self._peak,
                "occupancy_avg": round(
                    self._occupancy_sum / self._acquires, 2)
                    if self._acquires else 0.0,
                "overlap_ratio": round(self._inflight_ns / span, 2)
                    if span > 0 else 0.0,
                "blocked_ms": round(self._blocked_ns / 1e6, 2),
            }
