"""Tensor frames flowing through the pipeline.

The TPU-native analog of GstBuffer carrying N tensor memories
(ref: gst/nnstreamer/nnstreamer_plugin_api_impl.c —
gst_tensor_buffer_get_nth_memory / append_memory).

Key departure from the reference: a chunk may be **device-resident**
(a ``jax.Array`` living in HBM). Chained device-side elements hand arrays
to each other without materializing to host; only converter/decoder/sink
boundaries call :meth:`Chunk.host`. This is the zero-copy story on TPU —
the reference passes host pointers, we pass HBM references (SURVEY.md §7
hard part (b)). There is no 16-chunk packing limit; chunks are a list.
"""
from __future__ import annotations

import enum
from typing import Any, List, Optional, Sequence

import numpy as np

from .info import TensorInfo, TensorsInfo
from .meta import TensorMetaInfo
from .types import TensorType


def _is_device_array(x) -> bool:
    import jax
    return isinstance(x, jax.Array)


class BufferFlags(enum.IntFlag):
    NONE = 0
    DISCONT = 1     # stream discontinuity
    GAP = 2         # filler frame
    DROPPABLE = 4   # QoS may drop


class Chunk:
    """One tensor memory: a host ndarray, a device jax.Array, or a
    :class:`~..tensors.fetch.PendingHost` (a D2H fetch in flight, started
    by the filter's prefetch-host pool).

    ``meta`` is present on flexible/sparse streams (self-describing header,
    ref: GstTensorMetaInfo); static streams rely on negotiated caps.
    """

    __slots__ = ("_data", "meta")

    def __init__(self, data: Any, meta: Optional[TensorMetaInfo] = None):
        self._data = data
        self.meta = meta

    def _settle(self) -> Any:
        """Resolve an in-flight fetch (blocking) and cache the result."""
        from .fetch import PendingHost
        d = self._data
        if isinstance(d, PendingHost):
            d = self._data = d.resolve()
        return d

    # -- residency --------------------------------------------------------
    @property
    def is_device(self) -> bool:
        from .fetch import PendingHost
        d = self._data
        if isinstance(d, PendingHost):
            # still device-reachable until the fetch lands: chained
            # device-side elements keep HBM residency without waiting
            return d.dev is not None and not d.done
        return not isinstance(d, (np.ndarray, bytes, bytearray, memoryview))

    @property
    def raw(self) -> Any:
        """The underlying array, wherever it lives. For a chunk whose
        host fetch is in flight this is non-blocking while the device
        array is still reachable (device consumers proceed in HBM);
        otherwise it blocks for the fetched host copy."""
        from .fetch import PendingHost
        d = self._data
        if isinstance(d, PendingHost):
            if not d.done and d.dev is not None:
                return d.dev
            d = self._data = d.resolve()
        return d

    def host(self) -> np.ndarray:
        """Materialize to a host ndarray (D2H transfer if device-resident)."""
        d = self._settle()
        if isinstance(d, np.ndarray):
            return d
        if isinstance(d, (bytes, bytearray, memoryview)):
            return np.frombuffer(d, dtype=np.uint8)
        return np.asarray(d)

    def device(self, device=None, sharding=None):
        """Materialize on device (H2D transfer if host-resident)."""
        import jax
        from .fetch import PendingHost
        d = self._data
        if isinstance(d, PendingHost):
            # prefer the still-live device array: no wait, no H2D
            d = d.dev if d.dev is not None else self._settle()
        if _is_device_array(d) and device is None and sharding is None:
            return d
        if not _is_device_array(d) and not isinstance(d, np.ndarray):
            d = self.host()
        return jax.device_put(d,
                              sharding if sharding is not None else device)

    # -- shape/dtype ------------------------------------------------------
    @property
    def shape(self):
        d = self._data
        if isinstance(d, (bytes, bytearray, memoryview)):
            return (len(d),)
        return tuple(d.shape)

    @property
    def dtype(self):
        d = self._data
        if isinstance(d, (bytes, bytearray, memoryview)):
            return np.dtype(np.uint8)
        return np.dtype(d.dtype)

    @property
    def nbytes(self) -> int:
        d = self._data
        if isinstance(d, (bytes, bytearray, memoryview)):
            return len(d)
        return int(np.prod(self.shape)) * self.dtype.itemsize

    def to_info(self, name: Optional[str] = None) -> TensorInfo:
        return TensorInfo(name=name, type=TensorType.from_dtype(self.dtype),
                          shape=self.shape)

    def __repr__(self) -> str:
        loc = "dev" if self.is_device else "host"
        return f"Chunk<{loc}:{self.dtype}:{self.shape}>"


class Buffer:
    """One frame: ordered chunks + timing metadata.

    Timing fields are nanoseconds, mirroring GstBuffer pts/dts/duration.
    """

    __slots__ = ("chunks", "pts", "dts", "duration", "flags", "extras")

    def __init__(self, chunks: Sequence[Chunk] = (), pts: Optional[int] = None,
                 dts: Optional[int] = None, duration: Optional[int] = None,
                 flags: BufferFlags = BufferFlags.NONE):
        self.chunks: List[Chunk] = list(chunks)
        self.pts = pts
        self.dts = dts
        self.duration = duration
        self.flags = flags
        self.extras: dict = {}  # side-band metadata (e.g., crop coords, client id)

    @classmethod
    def from_arrays(cls, arrays: Sequence[Any], **kw) -> "Buffer":
        return cls([a if isinstance(a, Chunk) else Chunk(a) for a in arrays], **kw)

    def __len__(self) -> int:
        return len(self.chunks)

    def __getitem__(self, i: int) -> Chunk:
        return self.chunks[i]

    def __iter__(self):
        return iter(self.chunks)

    def append(self, chunk: Chunk) -> None:
        # racecheck: ok(buffers are single-owner: built by one thread, then handed off whole via queue/pad push)
        self.chunks.append(chunk)

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.chunks)

    def arrays(self) -> List[Any]:
        return [c.raw for c in self.chunks]

    def host_arrays(self) -> List[np.ndarray]:
        return [c.host() for c in self.chunks]

    def to_infos(self) -> TensorsInfo:
        return TensorsInfo(c.to_info() for c in self.chunks)

    def with_chunks(self, chunks: Sequence[Chunk]) -> "Buffer":
        """New buffer reusing this one's timing metadata."""
        b = Buffer(chunks, self.pts, self.dts, self.duration, self.flags)
        b.extras = dict(self.extras)
        return b

    def copy_meta_from(self, other: "Buffer") -> "Buffer":
        self.pts, self.dts = other.pts, other.dts
        self.duration, self.flags = other.duration, other.flags
        self.extras = dict(other.extras)
        return self

    def __repr__(self) -> str:
        return f"Buffer(pts={self.pts}, chunks={self.chunks!r})"
