"""Caps: stream capability descriptions + negotiation algebra.

A minimal, GStreamer-compatible caps model for tensor pipelines
(ref: caps handling in gst/nnstreamer/nnstreamer_plugin_api_impl.c —
gst_tensors_config_from_caps / gst_tensor_pad_caps_from_config; grammar in
include/tensor_typedef.h:90-132).

Grammar (reference-compatible subset)::

    other/tensors,format=static,num_tensors=2,
        types=(string)"uint8,float32",dimensions=(string)"3:224:224:1,10:1",
        framerate=(fraction)30/1

* ``(type)`` annotations are accepted and ignored.
* Quoted values may contain commas (multi-tensor types/dimensions lists).
* Int ranges ``[1,256]``, fraction ranges ``[0/1,2147483647/1]``, and
  alternative sets ``{a,b}`` are supported for negotiation templates.
* ``ANY`` caps intersect with everything.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Dict, List, Optional, Union

from .info import TensorsConfig, TensorsInfo
from .types import MIMETYPE_TENSORS, TensorFormat

__all__ = ["Caps", "CapsStructure", "IntRange", "FractionRange", "AltSet"]


@dataclass(frozen=True)
class IntRange:
    lo: int
    hi: int

    def __str__(self):
        return f"[{self.lo},{self.hi}]"


@dataclass(frozen=True)
class FractionRange:
    lo: Fraction
    hi: Fraction

    def __str__(self):
        return (f"[{self.lo.numerator}/{self.lo.denominator},"
                f"{self.hi.numerator}/{self.hi.denominator}]")


@dataclass(frozen=True)
class AltSet:
    values: tuple

    def __str__(self):
        return "{" + ",".join(_val_str(v) for v in self.values) + "}"


Value = Union[str, int, Fraction, IntRange, FractionRange, AltSet]


def _val_str(v: Value) -> str:
    if isinstance(v, Fraction):
        return f"{v.numerator}/{v.denominator}"
    if isinstance(v, str) and ("," in v or " " in v):
        return f'"{v}"'
    return str(v)


def _intersect_value(a: Value, b: Value) -> Optional[Value]:
    """Intersection of two field values; None = empty."""
    if isinstance(a, AltSet):
        hits = [r for v in a.values if (r := _intersect_value(v, b)) is not None]
        if not hits:
            return None
        return hits[0] if len(hits) == 1 else AltSet(tuple(hits))
    if isinstance(b, AltSet):
        return _intersect_value(b, a)
    if isinstance(a, IntRange) and isinstance(b, IntRange):
        lo, hi = max(a.lo, b.lo), min(a.hi, b.hi)
        if lo > hi:
            return None
        return lo if lo == hi else IntRange(lo, hi)
    if isinstance(a, IntRange):
        a, b = b, a
    if isinstance(b, IntRange) and isinstance(a, int):
        return a if b.lo <= a <= b.hi else None
    if isinstance(a, FractionRange) and isinstance(b, FractionRange):
        lo, hi = max(a.lo, b.lo), min(a.hi, b.hi)
        if lo > hi:
            return None
        return lo if lo == hi else FractionRange(lo, hi)
    if isinstance(a, FractionRange):
        a, b = b, a
    if isinstance(b, FractionRange) and isinstance(a, Fraction):
        return a if b.lo <= a <= b.hi else None
    return a if a == b else None


def _fixate_value(v: Value) -> Value:
    if isinstance(v, AltSet):
        return _fixate_value(v.values[0])
    if isinstance(v, IntRange):
        return v.lo
    if isinstance(v, FractionRange):
        # prefer a sane default rate inside the range, else the upper bound
        for cand in (Fraction(30, 1), Fraction(0, 1)):
            if v.lo <= cand <= v.hi:
                return cand
        return v.hi
    return v


def _parse_value(tok: str) -> Value:
    tok = tok.strip()
    if len(tok) >= 2 and tok[0] == tok[-1] and tok[0] in "\"'":
        return tok[1:-1]
    if tok.startswith("[") and tok.endswith("]"):
        lo, hi = tok[1:-1].split(",", 1)
        if "/" in lo or "/" in hi:
            return FractionRange(Fraction(lo.strip()), Fraction(hi.strip()))
        return IntRange(int(lo), int(hi))
    if tok.startswith("{") and tok.endswith("}"):
        return AltSet(tuple(_parse_value(t) for t in _split_top(tok[1:-1])))
    if "/" in tok:
        try:
            return Fraction(tok)
        except ValueError:
            return tok
    try:
        return int(tok)
    except ValueError:
        return tok


def _split_top(s: str) -> List[str]:
    """Split on commas not inside quotes/brackets/braces."""
    out, depth, quote, cur = [], 0, None, []
    for ch in s:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch in "[{(":
            depth += 1
            cur.append(ch)
        elif ch in "]})":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return [t for t in (t.strip() for t in out) if t]


class CapsStructure:
    """One media structure: name + fields."""

    def __init__(self, name: str, fields: Optional[Dict[str, Value]] = None):
        self.name = name
        self.fields: Dict[str, Value] = dict(fields or {})

    def intersect(self, other: "CapsStructure") -> Optional["CapsStructure"]:
        if self.name != other.name:
            return None
        merged: Dict[str, Value] = {}
        for k in set(self.fields) | set(other.fields):
            if k in self.fields and k in other.fields:
                v = _intersect_value(self.fields[k], other.fields[k])
                if v is None:
                    return None
                merged[k] = v
            else:
                merged[k] = self.fields.get(k, other.fields.get(k))
        return CapsStructure(self.name, merged)

    def is_fixed(self) -> bool:
        return not any(
            isinstance(v, (IntRange, FractionRange, AltSet))
            for v in self.fields.values())

    def fixate(self) -> "CapsStructure":
        return CapsStructure(
            self.name, {k: _fixate_value(v) for k, v in self.fields.items()})

    def __str__(self) -> str:
        parts = [self.name]
        for k, v in self.fields.items():
            parts.append(f"{k}={_val_str(v)}")
        return ",".join(parts)

    def __eq__(self, other) -> bool:
        return (isinstance(other, CapsStructure)
                and self.name == other.name and self.fields == other.fields)


class Caps:
    """An ordered list of alternative CapsStructures (preference order)."""

    def __init__(self, structures: "Union[str, List[CapsStructure], None]" = None):
        if structures is None:
            self.structures: List[CapsStructure] = []
            self.any = True
            return
        self.any = False
        if isinstance(structures, str):
            self.structures = _parse_caps(structures)
            if structures.strip() == "ANY":
                self.any = True
        else:
            self.structures = list(structures)

    # -- constructors -----------------------------------------------------
    @classmethod
    def ANY(cls) -> "Caps":
        return cls(None)

    @classmethod
    def from_config(cls, config: TensorsConfig) -> "Caps":
        """TensorsConfig -> fixed caps (ref: gst_tensor_pad_caps_from_config)."""
        fields: Dict[str, Value] = {"format": str(config.format)}
        if config.format == TensorFormat.STATIC and len(config.info):
            fields["num_tensors"] = len(config.info)
            fields["types"] = config.info.types_string()
            fields["dimensions"] = config.info.dims_string()
        fields["framerate"] = Fraction(config.rate_n, config.rate_d or 1)
        return cls([CapsStructure(MIMETYPE_TENSORS, fields)])

    @classmethod
    def template(cls, formats=("static", "flexible", "sparse")) -> "Caps":
        """Pad-template caps: any tensors stream of the given formats."""
        fmt: Value = formats[0] if len(formats) == 1 else AltSet(tuple(formats))
        return cls([CapsStructure(MIMETYPE_TENSORS, {
            "format": fmt,
            "framerate": FractionRange(Fraction(0, 1), Fraction(2 ** 31 - 1, 1)),
        })])

    # -- conversions ------------------------------------------------------
    def to_config(self) -> TensorsConfig:
        """Fixed caps -> TensorsConfig (ref: gst_tensors_config_from_caps)."""
        if self.any or not self.structures:
            raise ValueError("cannot convert non-fixed caps to config")
        s = self.structures[0]
        if s.name != MIMETYPE_TENSORS:
            raise ValueError(f"not a tensors caps: {s.name}")
        fmt = TensorFormat.from_string(str(s.fields.get("format", "static")))
        rate = s.fields.get("framerate", Fraction(0, 1))
        if not isinstance(rate, Fraction):
            rate = Fraction(0, 1)
        info = TensorsInfo()
        if fmt == TensorFormat.STATIC and "dimensions" in s.fields:
            info = TensorsInfo.make(
                str(s.fields["types"]), str(s.fields["dimensions"]))
            n = s.fields.get("num_tensors")
            if isinstance(n, int) and n != len(info):
                raise ValueError("num_tensors mismatch with dimensions list")
        return TensorsConfig(info, fmt, rate.numerator, rate.denominator)

    # -- algebra ----------------------------------------------------------
    def intersect(self, other: "Caps") -> "Caps":
        if self.any:
            return Caps(list(other.structures)) if not other.any else Caps.ANY()
        if other.any:
            return Caps(list(self.structures))
        out = []
        for a in self.structures:
            for b in other.structures:
                r = a.intersect(b)
                if r is not None:
                    out.append(r)
        return Caps(out)

    def can_intersect(self, other: "Caps") -> bool:
        return self.any or other.any or bool(self.intersect(other).structures)

    def is_fixed(self) -> bool:
        return (not self.any and len(self.structures) == 1
                and self.structures[0].is_fixed())

    def fixate(self) -> "Caps":
        if self.any:
            raise ValueError("cannot fixate ANY caps")
        if not self.structures:
            raise ValueError("cannot fixate EMPTY caps")
        return Caps([self.structures[0].fixate()])

    def is_empty(self) -> bool:
        return not self.any and not self.structures

    def __str__(self) -> str:
        if self.any:
            return "ANY"
        if not self.structures:
            return "EMPTY"
        return "; ".join(str(s) for s in self.structures)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Caps) and self.any == other.any
                and self.structures == other.structures)


def _parse_caps(s: str) -> List[CapsStructure]:
    s = s.strip()
    if s in ("ANY", "EMPTY", ""):
        return []
    structures = []
    for struct_str in s.split(";"):
        toks = _split_top(struct_str)
        if not toks:
            continue
        name = toks[0]
        fields: Dict[str, Value] = {}
        for tok in toks[1:]:
            if "=" not in tok:
                raise ValueError(f"bad caps field {tok!r}")
            k, v = tok.split("=", 1)
            v = v.strip()
            if v.startswith("(") and ")" in v:  # drop (type) annotation
                v = v[v.index(")") + 1:]
            fields[k.strip()] = _parse_value(v)
        structures.append(CapsStructure(name, fields))
    return structures
