"""Coalescing device->host fetch service.

On a tunneled dev chip a D2H fetch of a *computed* result costs a full
RPC round trip (measured 10-100 ms of latency depending on link weather,
regardless of payload size; ``copy_to_host_async`` does not hide it). A
pipeline whose decoder fetches one frame at a time is therefore capped
at ~1/RTT fps no matter how fast the model runs.

The fix is architectural: the filter enqueues each frame's outputs with
one :func:`submit_fetch` call and pushes the frame downstream
immediately, carrying :class:`PendingHost` handles instead of arrays. A
single fetcher thread drains **everything queued** into one batched
``jax.device_get`` per RPC — adaptive batching: at high fps many frames
share one round trip, at low fps each frame pays one. Measured on the
tunnel: 6.4 ms/frame sustained vs 85-100 ms/frame for frame-at-a-time
fetching, and unlike a fetch *pool* it cannot congest the link with N
concurrent RPCs.

Residency: a pending handle still carries its device array, so chained
device-side consumers (a second filter, an accelerated transform) keep
HBM residency and never wait on the fetch; only host boundaries block.
HBM lifetime is unchanged from a plain device-resident chunk — the
buffer is released when the handle resolves or the frame is dropped.

The reference has no analog (host pointers are free there); this is the
TPU-native cost model talking (SURVEY.md §7 hard part (b): device
residency, materialize only at host boundaries — here even the
materialization is pipelined and batched).
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

import numpy as np

# cap on arrays per RPC so one giant drain can't add unbounded latency
# to the frames queued behind it
_MAX_ARRAYS_PER_RPC = 256


class _Ticket:
    """One frame's fetch: a list of device arrays -> host arrays."""

    __slots__ = ("arrays", "results", "error", "_evt")

    def __init__(self, arrays: List[Any]):
        self.arrays: Optional[List[Any]] = arrays
        self.results: Optional[List[np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self._evt = threading.Event()

    @property
    def done(self) -> bool:
        return self._evt.is_set()

    def _deliver(self, results: Optional[List[np.ndarray]],
                 error: Optional[BaseException] = None) -> None:
        self.results = results
        self.error = error
        self.arrays = None  # the fetcher's refs go; HBM lifetime is now
        self._evt.set()     # governed by the PendingHost handles alone

    def wait(self) -> List[np.ndarray]:
        self._evt.wait()
        if self.error is not None:
            raise self.error
        assert self.results is not None
        return self.results


class _Coalescer:
    def __init__(self):
        self._q: List[_Ticket] = []
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        # achieved-depth accounting: frames (tickets) per device_get RPC
        # is THE number that says whether the service actually amortizes
        # the link round trip (1.0 = degenerated to frame-at-a-time)
        self._stats = {"rpcs": 0, "frames": 0, "arrays": 0}

    def stats(self, reset: bool = False) -> dict:
        with self._cv:
            out = dict(self._stats)
            if reset:
                self._stats.update(rpcs=0, frames=0, arrays=0)
        out["frames_per_rpc_avg"] = (
            out["frames"] / out["rpcs"] if out["rpcs"] else 0.0)
        return out

    def _account(self, n_tickets: int, n_arrays: int) -> None:
        with self._cv:
            self._stats["rpcs"] += 1
            self._stats["frames"] += n_tickets
            self._stats["arrays"] += n_arrays

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="nns-fetch", daemon=True)
            self._thread.start()

    def submit(self, ticket: _Ticket) -> None:
        with self._cv:
            self._ensure_thread()
            self._q.append(ticket)
            self._cv.notify()

    def _run(self) -> None:
        import time as _time

        import jax
        last_rpc = 0.0
        while True:
            with self._cv:
                while not self._q:
                    self._cv.wait()
            # adaptive linger (Nagle-style): on a slow link, draining the
            # instant the first ticket lands races the pipeline's refill
            # — the sink frees queue slots only when THIS delivery runs,
            # so tickets submitted a millisecond after the drain wait a
            # whole extra round trip. A pause of 5% of the last RPC
            # (capped 4 ms) lets stragglers join. The worst case is
            # bounded by construction: the pause never exceeds 5% of the
            # measured RPC time, so even a fast link moving big payloads
            # pays <=5% slower cadence, repaid by any batching gain at
            # all; tiny-payload RPCs (the latency-sensitive case) have
            # tiny durations and skip the pause entirely. Measured:
            # ~1.7-1.9x devres pipeline fps at ~100 ms RTT, unchanged at
            # sub-ms RTT. Skipped when the backlog already fills an RPC
            # — waiting could not deepen that batch, only delay it.
            linger = min(0.004, last_rpc * 0.05)
            if linger > 0.0005:
                with self._cv:
                    backlog = sum(len(t.arrays or ()) for t in self._q)
                if backlog < _MAX_ARRAYS_PER_RPC:
                    _time.sleep(linger)
            with self._cv:
                grab: List[_Ticket] = []
                n = 0
                while self._q and n < _MAX_ARRAYS_PER_RPC:
                    t = self._q.pop(0)
                    grab.append(t)
                    n += len(t.arrays or ())
            flat = [a for t in grab for a in (t.arrays or ())]
            t0 = _time.perf_counter()
            try:
                host = jax.device_get(flat)
                last_rpc = _time.perf_counter() - t0
                self._account(len(grab), len(flat))
            except BaseException:  # noqa: BLE001 - isolate per frame below
                # one poisoned array (donated buffer, transient RPC error)
                # must not fail every frame sharing the RPC: retry each
                # ticket alone so only the genuinely bad frame errors out.
                # The failed round trip still cost a full RTT: count it
                # (0 frames delivered) so frames_per_rpc_avg cannot read
                # BETTER than reality on an unhealthy link; account each
                # retry before delivering so a resolve-then-reset caller
                # never sees counts land after its reset. The failed
                # attempt still measured real link time — keep the
                # linger's RPC estimate live through error storms.
                last_rpc = _time.perf_counter() - t0
                self._account(0, 0)
                for t in grab:
                    t1 = _time.perf_counter()
                    try:
                        host1 = jax.device_get(t.arrays or [])
                        last_rpc = _time.perf_counter() - t1
                        self._account(1, len(t.arrays or ()))
                        t._deliver(host1)
                    except BaseException as exc:  # noqa: BLE001
                        self._account(0, 0)
                        t._deliver(None, exc)
                continue
            i = 0
            for t in grab:
                k = len(t.arrays or ())
                t._deliver(host[i:i + k])
                i += k


_coalescer = _Coalescer()


class PendingHost:
    """A device array whose host copy is in flight.

    Shape/dtype are known immediately (from the array's aval, no sync);
    :meth:`resolve` blocks until the coalescer's ``device_get`` lands.
    One ticket is shared by every output of a frame. ``dev`` keeps the
    device array reachable so device-side consumers stay in HBM without
    waiting; it is dropped at first resolution.
    """

    __slots__ = ("_ticket", "_index", "dev", "shape", "dtype")

    def __init__(self, ticket: _Ticket, index: int, dev):
        self._ticket = ticket
        self._index = index
        self.dev = dev
        self.shape = tuple(dev.shape)
        self.dtype = np.dtype(dev.dtype)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def done(self) -> bool:
        return self._ticket.done

    def resolve(self) -> np.ndarray:
        out = self._ticket.wait()[self._index]
        self.dev = None
        return out


def submit_fetch(outputs: Sequence[Any]) -> List[Any]:
    """Enqueue one coalesced fetch for all device-resident outputs of a
    frame; host arrays pass through untouched. Returns the outputs with
    device arrays replaced by :class:`PendingHost` handles."""
    import jax

    dev_idx = [i for i, o in enumerate(outputs)
               if isinstance(o, jax.Array)]
    if not dev_idx:
        return list(outputs)
    ticket = _Ticket([outputs[i] for i in dev_idx])
    _coalescer.submit(ticket)
    wrapped = list(outputs)
    for slot, i in enumerate(dev_idx):
        wrapped[i] = PendingHost(ticket, slot, outputs[i])
    return wrapped


def resolve(x: Any) -> Any:
    """Materialize ``x`` if it is a pending fetch; identity otherwise."""
    return x.resolve() if isinstance(x, PendingHost) else x


def fetch_stats(reset: bool = False) -> dict:
    """Coalescer counters: rpcs / frames / arrays since start (or last
    reset) plus ``frames_per_rpc_avg``, the achieved batching depth —
    the observability hook for "is the RTT actually being amortized"."""
    return _coalescer.stats(reset=reset)
