"""Coalescing device->host fetch service — compat façade.

The one-way D2H fetcher grew into the bidirectional transfer service in
:mod:`nnstreamer_tpu.tensors.transfer` (download + upload coalescing,
per-link in-flight windows). This module keeps the historical import
surface — ``submit_fetch`` / ``resolve`` / ``PendingHost`` /
``fetch_stats`` — alive for existing callers; new code should import
from ``tensors.transfer`` directly.
"""
from __future__ import annotations

from .transfer import (  # noqa: F401 — re-exported compat surface
    _MAX_ARRAYS_PER_RPC,
    PendingHost,
    _Coalescer,
    _Downloader,
    _Ticket,
    _downloader,
    fetch_stats,
    resolve,
    submit_fetch,
)

# historical name for the download-side singleton (tests drive it
# directly to pin per-ticket error isolation)
_coalescer = _downloader
