"""Per-memory self-describing tensor header for flexible/sparse streams.

Semantic equivalent of GstTensorMetaInfo and its (de)serialization
(ref: gst/nnstreamer/tensor_meta.c — gst_tensor_meta_info_parse_header /
update_header / append_header; struct at include/tensor_typedef.h:310-326).

Binary layout (little-endian, fixed 128 bytes):
    magic     u32   0x54504e4e ("NNPT")
    version   u32   1
    type      i32   TensorType value (-1 = unknown)
    format    i32   TensorFormat value
    media     i32   MediaType value
    rank      u32   number of valid dims
    dims      u32 x 16  innermost-first, 1-padded (reference dim order)
    nnz       u64   sparse: number of non-zero elements (0 otherwise)
    reserved        zero padding to 128 bytes
"""
from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field
from typing import Optional, Tuple

from .info import TensorInfo
from .types import RANK_LIMIT, MediaType, TensorFormat, TensorType

HEADER_MAGIC = 0x54504E4E
HEADER_VERSION = 1
HEADER_SIZE = 128

_FIXED = struct.Struct("<IIiiiI16IQ")  # 24 + 64 + 8 = 96 bytes, zero-pad to 128


@dataclass
class TensorMetaInfo:
    """Self-describing header prepended to each flexible/sparse chunk."""

    type: Optional[TensorType] = None
    format: TensorFormat = TensorFormat.FLEXIBLE
    media_type: MediaType = MediaType.TENSOR
    shape: Tuple[int, ...] = ()   # NumPy order, like TensorInfo
    nnz: int = 0                  # sparse only

    @classmethod
    def from_info(cls, info: TensorInfo,
                  format: TensorFormat = TensorFormat.FLEXIBLE,
                  media_type: MediaType = MediaType.TENSOR,
                  nnz: int = 0) -> "TensorMetaInfo":
        return cls(info.type, format, media_type, tuple(info.shape), nnz)

    def to_info(self) -> TensorInfo:
        return TensorInfo(type=self.type, shape=tuple(self.shape))

    @property
    def data_size_bytes(self) -> int:
        """Payload size for a dense chunk with this header."""
        if self.type is None:
            return 0
        return math.prod(self.shape or (0,)) * self.type.element_size

    def pack(self) -> bytes:
        if len(self.shape) > RANK_LIMIT:
            raise ValueError(
                f"rank {len(self.shape)} exceeds limit {RANK_LIMIT}")
        dims = list(reversed(self.shape))
        rank = len(dims)
        dims += [1] * (RANK_LIMIT - len(dims))
        body = _FIXED.pack(
            HEADER_MAGIC, HEADER_VERSION,
            int(self.type) if self.type is not None else -1,
            int(self.format), int(self.media_type), rank, *dims, self.nnz)
        return body + b"\x00" * (HEADER_SIZE - len(body))

    @classmethod
    def unpack(cls, data: bytes) -> "TensorMetaInfo":
        if len(data) < HEADER_SIZE:
            raise ValueError(f"header too short: {len(data)} < {HEADER_SIZE}")
        vals = _FIXED.unpack(bytes(data[:_FIXED.size]))
        magic, version, ttype, tformat, media, rank = vals[:6]
        dims, nnz = vals[6:6 + RANK_LIMIT], vals[6 + RANK_LIMIT]
        if magic != HEADER_MAGIC:
            raise ValueError(f"bad meta magic 0x{magic:08x}")
        if version != HEADER_VERSION:
            raise ValueError(f"unsupported meta version {version}")
        shape = tuple(reversed(dims[:rank]))
        return cls(
            TensorType(ttype) if ttype >= 0 else None,
            TensorFormat(tformat), MediaType(media), shape, nnz)
