"""Pipeline-less single invoke (the ML "single-shot" API).

≙ gst/nnstreamer/tensor_filter/tensor_filter_single.c — the GObject with
klass->invoke/start/stop behind the C ML Single-shot API. Shares the same
backend classes (and therefore the same PJRT client/process) as the
tensor_filter pipeline element, per BASELINE.json's north star.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

from .filters.base import Accelerator, FilterEvent, FilterProperties
from .filters.registry import detect_framework, find_filter
from .tensors.info import TensorsInfo


class SingleShot:
    """Open a model once, invoke synchronously (or async-callback) without
    building a pipeline."""

    def __init__(self, model: str, framework: str = "auto",
                 input_info: Optional[TensorsInfo] = None,
                 output_info: Optional[TensorsInfo] = None,
                 accelerator: str = "", custom: str = ""):
        models = tuple(model.split(","))
        if framework in ("auto", ""):
            framework = detect_framework(models)
        self.props = FilterProperties(
            framework=framework, model_files=models,
            input_info=input_info, output_info=output_info,
            accelerators=tuple(Accelerator.parse(accelerator)),
            custom_properties=custom)
        self.fw = find_filter(framework)()
        self._opened = False
        self._async_cb: Optional[Callable[[List[Any]], None]] = None

    def start(self) -> "SingleShot":
        if not self._opened:
            self.fw.open(self.props)
            self._opened = True
        return self

    def stop(self) -> None:
        if self._opened:
            self.fw.close()
            self._opened = False

    def __enter__(self) -> "SingleShot":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        if not self._opened:
            self.start()
        return self.fw.invoke(list(inputs))

    def set_async_callback(self, cb: Callable[[List[Any]], None]) -> None:
        self._async_cb = cb
        # user callbacks take just the outputs; drop the per-invoke ctx
        self.fw.set_async_dispatcher(lambda outputs, ctx=None: cb(outputs))

    def invoke_async(self, inputs: Sequence[Any], ctx: Any = None) -> None:
        if not self._opened:
            self.start()
        self.fw.invoke_async(list(inputs), ctx=ctx)

    def get_model_info(self):
        if not self._opened:
            self.start()
        return self.fw.get_model_info()
