"""Minimal FlatBuffers *builder* (serialization side).

Counterpart of the schema-less reader in interop/flatbuf.py — that one
was written to parse TFLite files; this one emits buffers for the
nnstreamer tensor schema (ref: ext/nnstreamer/include/nnstreamer.fbs).
Implemented from the FlatBuffers wire-format rules (little-endian,
buffers grow downward, tables point back at vtables); reader and writer
being independent implementations makes round-trip tests a real format
check, not self-confirmation.

Supported: scalar/struct/offset table fields, u8/u32/offset vectors,
strings. That covers the Tensors schema and similar message schemas.

Coordinates: the buffer is built back-to-front; every returned position
is a byte distance from the END of the final buffer to the START of the
object (the conventional uoffset space).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple


class Builder:
    def __init__(self):
        # bytes stored in reverse: final buffer = reversed(self._rev)
        self._rev = bytearray()
        self._minalign = 4
        self._vtables: Dict[Tuple, int] = {}
        self._fields: Optional[List[Tuple[int, int]]] = None
        self._table_mark = 0

    # -- low-level ----------------------------------------------------------
    @property
    def offset(self) -> int:
        """Bytes written so far = end-offset of the last written byte."""
        return len(self._rev)

    def _write(self, data: bytes) -> None:
        """Write toward the front of the final buffer."""
        self._rev.extend(reversed(data))

    def _align(self, size: int, extra: int = 0) -> None:
        self._minalign = max(self._minalign, size)
        while (len(self._rev) + extra) % size != 0:
            self._rev.append(0)

    def _scalar(self, fmt: str, value) -> None:
        self._write(struct.pack("<" + fmt, value))

    def _uoffset(self, target: int) -> None:
        """u32 relative offset: value = slot_pos - target_pos."""
        self._align(4, extra=4)
        slot = self.offset + 4
        assert target <= self.offset, "forward reference"
        self._scalar("I", slot - target)

    # -- strings / vectors ---------------------------------------------------
    def create_string(self, s: str) -> int:
        data = s.encode("utf-8")
        # align FIRST: writing back-to-front, padding emitted here lands
        # at higher addresses than the payload, i.e. after the NUL —
        # padding between length and chars would corrupt the string
        self._align(4, extra=len(data) + 1 + 4)
        self._write(b"\0")          # NUL sits after the chars
        self._write(data)
        self._scalar("I", len(data))
        return self.offset

    def create_vector_u8(self, data: bytes) -> int:
        self._align(4, extra=len(data) + 4)
        self._write(bytes(data))
        self._scalar("I", len(data))
        return self.offset

    def create_vector_u32(self, values) -> int:
        vals = [int(v) for v in values]
        self._align(4)
        for v in reversed(vals):
            self._scalar("I", v)
        self._scalar("I", len(vals))
        return self.offset

    def create_vector_offsets(self, offsets: List[int]) -> int:
        self._align(4)
        for off in reversed(offsets):
            self._uoffset(off)
        self._scalar("I", len(offsets))
        return self.offset

    # -- tables --------------------------------------------------------------
    _SCALAR_SIZE = {"b": 1, "B": 1, "h": 2, "H": 2, "i": 4, "I": 4,
                    "q": 8, "Q": 8, "f": 4, "d": 8}

    def start_table(self) -> None:
        assert self._fields is None, "nested start_table"
        self._fields = []
        self._table_mark = self.offset

    def add_scalar(self, fid: int, fmt: str, value, default=0) -> None:
        if value == default:
            return
        size = self._SCALAR_SIZE[fmt]
        self._align(size)
        self._scalar(fmt, value)
        self._fields.append((fid, self.offset))

    def add_offset(self, fid: int, target: Optional[int]) -> None:
        if not target:
            return
        self._uoffset(target)
        self._fields.append((fid, self.offset))

    def add_struct(self, fid: int, data: bytes, align: int = 4) -> None:
        """Structs are stored inline in the table."""
        self._align(align)
        self._write(data)
        self._fields.append((fid, self.offset))

    def end_table(self) -> int:
        fields, self._fields = self._fields, None
        self._align(4, extra=4)
        table_pos = self.offset + 4      # start once the soffset is written
        nfields = (max(f[0] for f in fields) + 1) if fields else 0
        # vtable slots: distance from table start back to each field
        slots = [0] * nfields
        for fid, off in fields:
            slots[fid] = table_pos - off
        table_size = table_pos - self._table_mark
        vt_key = (table_size, tuple(slots))
        existing = self._vtables.get(vt_key)
        if existing is not None:
            # shared vtable written earlier: negative signed distance
            self._scalar("i", existing - table_pos)
            return self.offset
        # fresh vtable placed immediately before the table in address
        # space, so soffset = vtable_pos - table_pos = +vt_bytes exactly
        vt_bytes = 4 + 2 * nfields
        self._scalar("i", vt_bytes)
        self._write(struct.pack("<HH", vt_bytes, table_size)
                    + b"".join(struct.pack("<H", s) for s in slots))
        self._vtables[vt_key] = self.offset  # vtable position
        return table_pos

    def finish(self, root: int) -> bytes:
        self._align(self._minalign, extra=4)
        self._uoffset(root)
        return bytes(reversed(self._rev))
