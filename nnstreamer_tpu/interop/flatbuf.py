"""Minimal FlatBuffers reader — enough to walk a .tflite model.

No generated code, no `flatbuffers` dependency: just the wire format
(https://flatbuffers.dev/internals): a root uoffset, tables with signed
vtable offsets, vtables of uint16 field offsets, vectors/strings with a
uint32 length prefix. Field ids follow the schema declaration order.

Used by interop/tflite.py; the reference links the real FlatBuffers C++
runtime instead (ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc
and tensor_decoder/tensordec-flatbuf.cc).
"""
from __future__ import annotations

import struct
from typing import Optional

import numpy as np


class FlatBuf:
    """Random-access reader over one FlatBuffers blob."""

    def __init__(self, data: bytes):
        self.buf = memoryview(data)

    # -- scalars -----------------------------------------------------------
    def u8(self, pos: int) -> int:
        return self.buf[pos]

    def i8(self, pos: int) -> int:
        return struct.unpack_from("<b", self.buf, pos)[0]

    def u16(self, pos: int) -> int:
        return struct.unpack_from("<H", self.buf, pos)[0]

    def i16(self, pos: int) -> int:
        return struct.unpack_from("<h", self.buf, pos)[0]

    def u32(self, pos: int) -> int:
        return struct.unpack_from("<I", self.buf, pos)[0]

    def i32(self, pos: int) -> int:
        return struct.unpack_from("<i", self.buf, pos)[0]

    def i64(self, pos: int) -> int:
        return struct.unpack_from("<q", self.buf, pos)[0]

    def f32(self, pos: int) -> float:
        return struct.unpack_from("<f", self.buf, pos)[0]

    def f64(self, pos: int) -> float:
        return struct.unpack_from("<d", self.buf, pos)[0]

    # -- structure ---------------------------------------------------------
    def root(self) -> int:
        """Position of the root table."""
        return self.u32(0)

    def field(self, table: int, fid: int) -> Optional[int]:
        """Absolute position of field `fid`'s data in `table`, or None if
        absent (deserializers must then use the schema default)."""
        vtable = table - self.i32(table)
        vtsize = self.u16(vtable)
        entry = 4 + fid * 2
        if entry >= vtsize:
            return None
        voff = self.u16(vtable + entry)
        if voff == 0:
            return None
        return table + voff

    def indirect(self, pos: int) -> int:
        """Follow a uoffset at `pos` (table/vector/string fields)."""
        return pos + self.u32(pos)

    # -- field convenience -------------------------------------------------
    def field_scalar(self, table: int, fid: int, kind: str, default=0):
        pos = self.field(table, fid)
        if pos is None:
            return default
        return getattr(self, kind)(pos)

    def field_table(self, table: int, fid: int) -> Optional[int]:
        pos = self.field(table, fid)
        return None if pos is None else self.indirect(pos)

    def field_string(self, table: int, fid: int,
                     default: str = "") -> str:
        pos = self.field(table, fid)
        if pos is None:
            return default
        spos = self.indirect(pos)
        n = self.u32(spos)
        return bytes(self.buf[spos + 4:spos + 4 + n]).decode("utf-8")

    # -- vectors -----------------------------------------------------------
    def vector_len(self, vpos: int) -> int:
        return self.u32(vpos)

    def field_vector(self, table: int, fid: int) -> Optional[int]:
        """Position of the length prefix of a vector field, or None."""
        pos = self.field(table, fid)
        return None if pos is None else self.indirect(pos)

    def vector_tables(self, vpos: int):
        """Iterate table positions in a [Table] vector."""
        n = self.u32(vpos)
        for i in range(n):
            yield self.indirect(vpos + 4 + i * 4)

    def field_np(self, table: int, fid: int, dtype) -> Optional[np.ndarray]:
        """A scalar vector field as a numpy array (zero-copy view)."""
        vpos = self.field_vector(table, fid)
        if vpos is None:
            return None
        n = self.u32(vpos)
        dt = np.dtype(dtype).newbyteorder("<")
        return np.frombuffer(self.buf, dtype=dt, count=n,
                             offset=vpos + 4)
