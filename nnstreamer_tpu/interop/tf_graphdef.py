"""TensorFlow GraphDef (.pb) importer: frozen graphs lower to one
jittable JAX function.

≙ ext/nnstreamer/tensor_filter/tensor_filter_tensorflow.cc (the
reference feeds the .pb to the TF C API and runs a session). Here the
GraphDef protobuf is walked with the schema-less wire codec
(interop/protowire.py) — no tensorflow dependency — and each node
lowers to a jax/lax op, so a frozen graph becomes a single XLA program
on the MXU like every other backend.

Supported op set mirrors the importer policy of interop/tflite.py:
common inference ops lower; anything else raises NotImplementedError
naming the op (fail loud, never silently wrong).

GraphDef wire schema (tensorflow/core/framework/graph.proto):
  GraphDef.node = 1 (NodeDef)
  NodeDef: name=1, op=2, input=3 (repeated), device=4, attr=5 (map)
  map entry: key=1, value=2 (AttrValue)
  AttrValue: s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8, list=1
  AttrValue.ListValue: s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8
  TensorProto: dtype=1, tensor_shape=2, tensor_content=4, float_val=5,
               int_val=7, int64_val=10 (content preferred; *_val fallback)
  TensorShapeProto: dim=2 -> Dim: size=1, name=2
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..tensors.info import TensorInfo, TensorsInfo
from ..tensors.types import TensorType
from .protowire import as_f32, as_sint, decode, packed_varints

# tensorflow DataType enum -> numpy dtype (types.proto)
_TF_DTYPES = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 5: np.int16,
    6: np.int8, 9: np.int64, 10: np.bool_, 17: np.uint16, 22: np.uint32,
    23: np.uint64, 19: np.float16,
}




@dataclasses.dataclass
class _Node:
    name: str
    op: str
    inputs: List[str]
    attrs: Dict[str, Dict[int, list]]  # attr name -> decoded AttrValue


@dataclasses.dataclass
class TFModel:
    fn: Callable
    input_info: TensorsInfo
    output_info: TensorsInfo
    path: str


# -- proto walking -------------------------------------------------------------

def _attr_shape(av: Dict[int, list]) -> Tuple[int, ...]:
    shp = decode(av[7][0]) if 7 in av else {}
    dims = []
    for d in shp.get(2, []):
        dd = decode(d)
        size = int(dd.get(1, [0])[0])
        # int64 may arrive as unsigned varint; -1 (unknown) wraps huge
        if size > (1 << 62):
            size = size - (1 << 64)
        dims.append(size)
    return tuple(dims)


def _attr_tensor(av: Dict[int, list]) -> np.ndarray:
    tp = decode(av[8][0])
    dtype = _TF_DTYPES.get(int(tp.get(1, [1])[0]), np.float32)
    dims: List[int] = []
    if 2 in tp:
        shp = decode(tp[2][0])
        for d in shp.get(2, []):
            dims.append(int(decode(d).get(1, [0])[0]))
    if 4 in tp and tp[4][0]:
        arr = np.frombuffer(tp[4][0], dtype=np.dtype(dtype).newbyteorder("<"))
    elif 5 in tp:      # float_val (packed or repeated)
        raw = tp[5][0] if isinstance(tp[5][0], bytes) else None
        if raw is not None:
            arr = np.frombuffer(raw, "<f4")
        else:
            arr = np.asarray([as_f32(v) for v in tp[5]], np.float32)
    elif 7 in tp:      # int_val (field 7; 8 is string_val)
        vals = (packed_varints(tp[7][0]) if isinstance(tp[7][0], bytes)
                else [int(v) for v in tp[7]])
        arr = np.asarray([as_sint(v) for v in vals], np.int64) \
            .astype(np.int32)
    elif 10 in tp:     # int64_val
        vals = (packed_varints(tp[10][0]) if isinstance(tp[10][0], bytes)
                else [int(v) for v in tp[10]])
        arr = np.asarray([as_sint(v) for v in vals], np.int64)
    else:
        arr = np.zeros(0, dtype)
    arr = arr.astype(dtype)
    if dims:
        if arr.size == 1 and int(np.prod(dims)) > 1:
            arr = np.full(dims, arr.reshape(-1)[0])  # splat scalar
        arr = arr.reshape(dims)
    return arr


def _parse(data: bytes) -> List[_Node]:
    g = decode(data)
    nodes = []
    for nb in g.get(1, []):
        nd = decode(nb)
        attrs: Dict[str, Dict[int, list]] = {}
        for ab in nd.get(5, []):
            entry = decode(ab)
            key = entry.get(1, [b""])[0].decode()
            attrs[key] = decode(entry.get(2, [b""])[0])
        nodes.append(_Node(
            name=nd.get(1, [b""])[0].decode(),
            op=nd.get(2, [b""])[0].decode(),
            inputs=[i.decode() for i in nd.get(3, [])],
            attrs=attrs))
    return nodes


def _canon(ref: str) -> str:
    """'node:0' -> 'node'; control deps '^node' handled by the caller."""
    return ref.split(":", 1)[0]


# -- lowering ------------------------------------------------------------------

def _pool(x, ksize, strides, padding, reduce_fn, init):
    import jax.lax as lax
    return lax.reduce_window(x, init, reduce_fn,
                             window_dimensions=tuple(ksize),
                             window_strides=tuple(strides),
                             padding=padding)


class _Lowerer:
    def __init__(self, nodes: List[_Node]):
        self.nodes = {n.name: n for n in nodes}
        self.order = nodes

    def attr_i(self, n: _Node, key: str, default: int = 0) -> int:
        av = n.attrs.get(key)
        return int(av[3][0]) if av and 3 in av else default

    def attr_b(self, n: _Node, key: str, default: bool = False) -> bool:
        av = n.attrs.get(key)
        return bool(av[5][0]) if av and 5 in av else default

    def attr_f(self, n: _Node, key: str, default: float = 0.0) -> float:
        av = n.attrs.get(key)
        return as_f32(av[4][0]) if av and 4 in av else default

    def attr_s(self, n: _Node, key: str, default: str = "") -> str:
        av = n.attrs.get(key)
        return av[2][0].decode() if av and 2 in av else default

    def attr_ilist(self, n: _Node, key: str) -> List[int]:
        av = n.attrs.get(key)
        if not av or 1 not in av:
            return []
        lst = decode(av[1][0])
        raw = lst.get(3, [])
        if len(raw) == 1 and isinstance(raw[0], bytes):
            return [v for v in packed_varints(raw[0])]
        return [int(v) for v in raw]

    def lower(self, n: _Node, env: Dict[str, Any]):
        import jax.numpy as jnp
        import jax.nn
        import jax.lax as lax
        ins = [env[_canon(i)] for i in n.inputs if not i.startswith("^")]
        op = n.op
        if op in ("Identity", "StopGradient", "PreventGradient", "CheckNumerics"):
            return ins[0]
        if op in ("Add", "AddV2"):
            return ins[0] + ins[1]
        if op == "Sub":
            return ins[0] - ins[1]
        if op == "Mul":
            return ins[0] * ins[1]
        if op in ("RealDiv", "Div"):
            return ins[0] / ins[1]
        if op == "Maximum":
            return jnp.maximum(ins[0], ins[1])
        if op == "Minimum":
            return jnp.minimum(ins[0], ins[1])
        if op == "MatMul":
            a, b = ins
            if self.attr_b(n, "transpose_a"):
                a = a.T
            if self.attr_b(n, "transpose_b"):
                b = b.T
            return a @ b
        if op == "BiasAdd":
            return ins[0] + ins[1]
        if op == "Relu":
            return jax.nn.relu(ins[0])
        if op == "Relu6":
            return jnp.clip(ins[0], 0, 6)
        if op == "Softmax":
            return jax.nn.softmax(ins[0], axis=-1)
        if op == "Sigmoid":
            return jax.nn.sigmoid(ins[0])
        if op == "Tanh":
            return jnp.tanh(ins[0])
        if op == "Sqrt":
            return jnp.sqrt(ins[0])
        if op == "Rsqrt":
            return lax.rsqrt(ins[0])
        if op == "Exp":
            return jnp.exp(ins[0])
        if op == "Neg":
            return -ins[0]
        if op == "Square":
            return ins[0] * ins[0]
        if op == "Reshape":
            return jnp.reshape(ins[0], [int(d) for d in
                                        np.asarray(ins[1]).reshape(-1)])
        if op == "Squeeze":
            dims = self.attr_ilist(n, "squeeze_dims") or None
            return jnp.squeeze(ins[0], axis=tuple(dims) if dims else None)
        if op == "ExpandDims":
            return jnp.expand_dims(ins[0], int(np.asarray(ins[1])))
        if op in ("ConcatV2", "Concat"):
            if op == "ConcatV2":
                axis = int(np.asarray(ins[-1]))
                parts = ins[:-1]
            else:
                axis = int(np.asarray(ins[0]))
                parts = ins[1:]
            return jnp.concatenate(parts, axis=axis)
        if op == "Pad":
            pads = np.asarray(ins[1]).astype(int)
            return jnp.pad(ins[0], [(int(a), int(b)) for a, b in pads])
        if op == "Mean":
            axes = tuple(int(a) for a in np.asarray(ins[1]).reshape(-1))
            keep = self.attr_b(n, "keep_dims")
            return jnp.mean(ins[0], axis=axes, keepdims=keep)
        if op in ("Conv2D", "DepthwiseConv2dNative"):
            x, w = ins
            strides = self.attr_ilist(n, "strides") or [1, 1, 1, 1]
            padding = self.attr_s(n, "padding", "SAME")
            if self.attr_s(n, "data_format", "NHWC") != "NHWC":
                raise NotImplementedError("tf import: only NHWC conv")
            dil = self.attr_ilist(n, "dilations")
            if dil and dil != [1, 1, 1, 1]:
                # fail loud rather than silently computing the
                # non-atrous variant (importer policy)
                raise NotImplementedError(
                    f"tf import: dilated conv not supported "
                    f"(dilations={dil}, node {n.name!r})")
            fgc = 1
            if op == "DepthwiseConv2dNative":
                # HWIM -> HWI(M) with feature_group_count = in_channels
                h, wd, cin, mult = w.shape
                w = w.reshape(h, wd, 1, cin * mult)
                fgc = cin
            return lax.conv_general_dilated(
                x, w, window_strides=tuple(strides[1:3]), padding=padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=fgc)
        if op in ("MaxPool", "AvgPool"):
            ksize = self.attr_ilist(n, "ksize") or [1, 1, 1, 1]
            strides = self.attr_ilist(n, "strides") or [1, 1, 1, 1]
            padding = self.attr_s(n, "padding", "VALID")
            if op == "MaxPool":
                return _pool(ins[0], ksize, strides, padding,
                             lax.max, -jnp.inf)
            s = _pool(ins[0], ksize, strides, padding, lax.add, 0.0)
            ones = jnp.ones_like(ins[0])
            cnt = _pool(ones, ksize, strides, padding, lax.add, 0.0)
            return s / cnt
        if op in ("FusedBatchNorm", "FusedBatchNormV3"):
            x, scale, offset, mean, var = ins[:5]
            eps = self.attr_f(n, "epsilon", 1e-3)
            inv = scale * lax.rsqrt(var + eps)
            return x * inv + (offset - mean * inv)
        raise NotImplementedError(
            f"tf import: unsupported GraphDef op {op!r} (node {n.name!r})")


def load(path: str) -> TFModel:
    with open(path, "rb") as f:
        nodes = _parse(f.read())
    if not nodes:
        raise ValueError(f"{path}: empty or unparsable GraphDef")
    consts: Dict[str, np.ndarray] = {}
    placeholders: List[_Node] = []
    for n in nodes:
        if n.op == "Const":
            consts[n.name] = _attr_tensor(n.attrs["value"])
        elif n.op == "Placeholder":
            placeholders.append(n)
    consumed = {_canon(i) for n in nodes for i in n.inputs
                if not i.startswith("^")}
    outputs = [n.name for n in nodes
               if n.name not in consumed and n.op not in ("Const",
                                                          "Placeholder",
                                                          "NoOp")]
    if not outputs:
        raise ValueError(f"{path}: no output nodes found")
    lower = _Lowerer(nodes)

    def fn(*inputs):
        env: Dict[str, Any] = dict(consts)
        for ph, x in zip(placeholders, inputs):
            env[ph.name] = x
        for n in lower.order:
            if n.op in ("Const", "Placeholder", "NoOp"):
                continue
            env[n.name] = lower.lower(n, env)
        return [env[o] for o in outputs]

    def _ph_info(ph: _Node) -> TensorInfo:
        dt = _TF_DTYPES.get(int(ph.attrs.get("dtype", {}).get(6, [1])[0]),
                            np.float32)
        shape = tuple(1 if d < 0 else d
                      for d in _attr_shape(ph.attrs.get("shape", {})))
        return TensorInfo(ph.name, TensorType.from_dtype(np.dtype(dt)),
                          shape or (1,))

    in_info = TensorsInfo(_ph_info(p) for p in placeholders)
    # trace output shapes/dtypes without running the graph
    import jax
    zeros = [np.zeros(i.shape, i.type.np_dtype) for i in in_info]
    out_shapes = jax.eval_shape(fn, *zeros)
    out_info = TensorsInfo(
        TensorInfo(name, TensorType.from_dtype(s.dtype), tuple(s.shape))
        for name, s in zip(outputs, out_shapes))
    return TFModel(fn=fn, input_info=in_info, output_info=out_info,
                   path=path)
