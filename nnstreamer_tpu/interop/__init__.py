"""Interop: importers for foreign model formats and wire codecs.

The reference ships ~30 native backend subplugins (ext/nnstreamer/
tensor_filter/). On TPU they collapse into importers: each foreign format
is parsed host-side and lowered to one jittable JAX function, so every
model — whatever its origin — runs through the same XLA path. Modules:

- flatbuf: minimal generic FlatBuffers reader (no codegen, no deps)
- tflite: .tflite model parser + op-by-op lowering to JAX
  (≙ ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc)
- onnx: .onnx protobuf parser + lowering
  (≙ ext/nnstreamer/tensor_filter/tensor_filter_onnxruntime.cc)
"""
