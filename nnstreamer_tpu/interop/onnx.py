"""ONNX model importer: .onnx protobuf -> one jittable JAX function.

≙ ext/nnstreamer/tensor_filter/tensor_filter_onnxruntime.cc (the
reference wraps the onnxruntime C++ session). Here the graph is parsed
with the schema-less protobuf reader (interop/protowire.py) and lowered
op-by-op to JAX, so ONNX models run on the same XLA path as everything
else. Supports the float op set plus the QOperator quantized ops
(QLinearConv/QLinearAdd/QLinearGlobalAveragePool/QLinearMatMul) in float
simulation: weights dequantize at import, activations stay float and are
clamped to each quantized tensor's representable range (see
interop/tflite.py for the same technique).

Layout stays NCHW as ONNX declares it — XLA's layout assignment handles
the TPU-side physical layout.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..tensors.info import TensorInfo, TensorsInfo
from ..tensors.types import TensorType
from . import protowire as pw

# TensorProto.DataType
_ELEM_NP = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
            5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
            10: np.float16, 11: np.float64, 12: np.uint32, 13: np.uint64}


@dataclasses.dataclass
class _Node:
    op: str
    inputs: List[str]
    outputs: List[str]
    attrs: Dict[str, Any]


@dataclasses.dataclass
class ONNXModel:
    fn: Callable
    input_info: TensorsInfo
    output_info: TensorsInfo
    path: str


# -- protobuf walking ------------------------------------------------------

def _parse_tensor_proto(data: bytes) -> Tuple[str, np.ndarray]:
    """TensorProto -> (name, ndarray)."""
    msg = pw.decode(data)
    dims = [pw.as_sint(d) for d in msg.get(1, [])]
    dtype = _ELEM_NP[msg.get(2, [1])[0]]
    name = msg.get(8, [b""])[0].decode()
    if 9 in msg:  # raw_data
        arr = np.frombuffer(msg[9][0], dtype=dtype)
    elif 4 in msg and dtype == np.float32:  # packed float_data
        raw = msg[4][0] if isinstance(msg[4][0], bytes) else None
        if raw is not None:
            arr = np.frombuffer(raw, np.float32)
        else:
            arr = np.array([pw.as_f32(v) for v in msg[4]], np.float32)
    elif 7 in msg:  # int64_data
        raw = msg[7][0] if isinstance(msg[7][0], bytes) else None
        vals = pw.packed_varints(raw) if raw is not None else msg[7]
        arr = np.array([pw.as_sint(v) for v in vals], np.int64)
    elif 5 in msg:  # int32_data (also holds u8/i8 payloads)
        raw = msg[5][0] if isinstance(msg[5][0], bytes) else None
        vals = pw.packed_varints(raw) if raw is not None else msg[5]
        arr = np.array([pw.as_sint(v) for v in vals]).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    return name, arr.reshape(dims) if dims else arr.reshape(())


def _parse_attr(data: bytes) -> Tuple[str, Any]:
    msg = pw.decode(data)
    name = msg[1][0].decode()
    atype = msg.get(20, [0])[0]
    if atype == 1:   # FLOAT
        return name, pw.as_f32(msg[2][0])
    if atype == 2:   # INT
        return name, pw.as_sint(msg[3][0])
    if atype == 3:   # STRING
        return name, msg[4][0].decode()
    if atype == 4:   # TENSOR
        return name, _parse_tensor_proto(msg[5][0])[1]
    if atype == 6:   # FLOATS
        vals = msg.get(7, [])
        if vals and isinstance(vals[0], bytes):
            return name, np.frombuffer(vals[0], "<f4").tolist()
        return name, [pw.as_f32(v) for v in vals]
    if atype == 7:   # INTS
        vals = msg.get(8, [])
        if vals and isinstance(vals[0], bytes):
            return name, [pw.as_sint(v) for v in pw.packed_varints(vals[0])]
        return name, [pw.as_sint(v) for v in vals]
    return name, None


def _parse_value_info(data: bytes) -> Tuple[str, Any, Tuple[int, ...]]:
    vi = pw.decode(data)
    name = vi[1][0].decode()
    dtype, shape = np.float32, ()
    if 2 in vi:
        t = pw.decode(vi[2][0])
        if 1 in t:  # tensor_type
            tt = pw.decode(t[1][0])
            dtype = _ELEM_NP.get(tt.get(1, [1])[0], np.float32)
            dims = []
            if 2 in tt:
                for db in pw.decode(tt[2][0]).get(1, []):
                    d = pw.decode(db)
                    dims.append(int(pw.as_sint(d[1][0])) if 1 in d else 1)
            shape = tuple(dims)
    return name, dtype, shape


def parse(path: str):
    with open(path, "rb") as f:
        model = pw.decode(f.read())
    graph = pw.decode(model[7][0])
    inits: Dict[str, np.ndarray] = {}
    for tb in graph.get(5, []):
        name, arr = _parse_tensor_proto(tb)
        inits[name] = arr
    nodes: List[_Node] = []
    for nb in graph.get(1, []):
        n = pw.decode(nb)
        nodes.append(_Node(
            op=n[4][0].decode(),
            inputs=[v.decode() for v in n.get(1, [])],
            outputs=[v.decode() for v in n.get(2, [])],
            attrs=dict(_parse_attr(ab) for ab in n.get(5, []))))
    g_in = [_parse_value_info(vb) for vb in graph.get(11, [])
            if pw.decode(vb)[1][0].decode() not in inits]
    g_out = [_parse_value_info(vb) for vb in graph.get(12, [])]
    return nodes, inits, g_in, g_out


# -- lowering --------------------------------------------------------------

def _conv(lax, jnp, x, w, b, attrs, group=1):
    strides = tuple(attrs.get("strides", [1, 1]))
    dil = tuple(attrs.get("dilations", [1, 1]))
    pads = attrs.get("pads")
    if attrs.get("auto_pad", "NOTSET") in ("SAME_UPPER", "SAME_LOWER"):
        padding = "SAME"
    elif pads:
        n = len(pads) // 2
        padding = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    else:
        padding = "VALID"
    y = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding, rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=group,
        preferred_element_type=jnp.float32)
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


def _dequant_w(w: np.ndarray, scale: np.ndarray,
               zp: np.ndarray) -> np.ndarray:
    """Weights to float; per-output-channel scale broadcasts on axis 0."""
    s = np.asarray(scale, np.float64)
    z = np.asarray(zp, np.float64)
    if s.ndim == 0 or s.size == 1:
        return ((w.astype(np.float64) - z.reshape(()) if z.size == 1
                 else w.astype(np.float64) - z) * s.reshape(())) \
            .astype(np.float32)
    bshape = [1] * w.ndim
    bshape[0] = s.size
    return ((w.astype(np.float64) - z.reshape(bshape))
            * s.reshape(bshape)).astype(np.float32)


def _qrange_clip(jnp, y, scale, zp, dtype):
    info = np.iinfo(dtype)
    s = float(np.asarray(scale).reshape(-1)[0])
    z = float(np.asarray(zp).reshape(-1)[0])
    return jnp.clip(y, (info.min - z) * s, (info.max - z) * s)


def _lower(nodes: List[_Node], inits: Dict[str, np.ndarray],
           g_in, g_out) -> Callable:
    import jax.numpy as jnp
    from jax import lax

    consts: Dict[str, Any] = dict(inits)

    # quantized graph boundaries: a u8/i8 graph input is consumed by a
    # DequantizeLinear (whose scale/zp dequantize it here at the boundary);
    # a u8/i8 graph output is produced by a QuantizeLinear (requantize at
    # the boundary so the wire dtype matches the declared signature)
    in_q: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    out_q: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    quant_names = {name for name, dtype, _ in g_in + g_out
                   if np.dtype(dtype) in (np.dtype(np.uint8),
                                          np.dtype(np.int8))}
    # exporters sometimes emit scale/zero_point as Constant nodes rather
    # than initializers — fold those in before the boundary scan
    bound = dict(inits)
    for node in nodes:
        if node.op == "Constant" and node.outputs:
            v = node.attrs.get("value")
            if v is not None:
                bound.setdefault(node.outputs[0], np.asarray(v))

    def _qparams(node, default_zp_dtype):
        if node.inputs[1] not in bound:
            raise NotImplementedError(
                f"{node.op} at a quantized graph boundary needs a "
                f"compile-time scale; {node.inputs[1]!r} is not an "
                "initializer or Constant")
        scale = bound[node.inputs[1]]
        zp = bound.get(node.inputs[2]) if len(node.inputs) > 2 \
            and node.inputs[2] else None
        return scale, (zp if zp is not None
                       else np.zeros(1, default_zp_dtype))

    for node in nodes:
        if node.op == "DequantizeLinear" and node.inputs[0] in quant_names:
            in_q[node.inputs[0]] = _qparams(node, np.int64)
        if node.op == "QuantizeLinear" and node.outputs[0] in quant_names:
            out_q[node.outputs[0]] = _qparams(node, np.uint8)

    def fn(*args):
        env: Dict[str, Any] = {}
        for (name, dtype, shape), x in zip(g_in, args):
            if tuple(x.shape) != shape and int(np.prod(shape)) == x.size:
                x = x.reshape(shape)
            if name in in_q:
                scale, zp = in_q[name]
                x = (x.astype(jnp.float32)
                     - float(np.asarray(zp).reshape(-1)[0])) \
                    * float(np.asarray(scale).reshape(-1)[0])
            env[name] = x

        def val(name: str):
            if name in env:
                return env[name]
            if name in consts:
                return consts[name]
            raise KeyError(f"onnx tensor {name!r} not materialized")

        def npval(name: str) -> np.ndarray:
            v = val(name)
            if isinstance(v, np.ndarray):
                return v
            raise NotImplementedError(
                f"onnx: need compile-time constant {name!r}")

        for node in nodes:
            outs = _eval_node(node, val, npval, jnp, lax)
            for oname, oval in zip(node.outputs, outs):
                env[oname] = oval
        results = []
        for name, dtype, _ in g_out:
            y = jnp.asarray(val(name))
            if name in out_q:
                scale, zp = out_q[name]
                info = np.iinfo(dtype)
                q = jnp.round(y / float(np.asarray(scale).reshape(-1)[0])) \
                    + float(np.asarray(zp).reshape(-1)[0])
                y = jnp.clip(q, info.min, info.max).astype(dtype)
            results.append(y)
        return results

    return fn


def _eval_node(node: _Node, val, npval, jnp, lax) -> List[Any]:
    op, a = node.op, node.attrs
    i = node.inputs

    def qval(x_idx: int, scale_idx: int, zp_idx: int):
        """A QLinear op's activation operand: runtime values are already
        float (simulation), but quantized CONSTANTS (e.g. a bias fed as a
        u8 initializer) must dequantize with their scale/zp inputs."""
        v = val(i[x_idx])
        if isinstance(v, np.ndarray) and v.dtype in (np.uint8, np.int8):
            return _dequant_w(v, npval(i[scale_idx]), npval(i[zp_idx]))
        return v

    if op == "Conv":
        w = np.asarray(npval(i[1]), np.float32)
        b = np.asarray(npval(i[2]), np.float32) if len(i) > 2 else None
        return [_conv(lax, jnp, val(i[0]), jnp.asarray(w), b, a,
                      int(a.get("group", 1)))]

    if op == "QLinearConv":
        x = val(i[0])
        w = _dequant_w(npval(i[3]), npval(i[4]), npval(i[5]))
        b = None
        if len(i) > 8:
            # int32 bias, scale = x_scale * w_scale (per channel)
            bs = np.asarray(npval(i[1]), np.float64) * \
                np.asarray(npval(i[4]), np.float64).reshape(-1)
            b = (npval(i[8]).astype(np.float64) * bs).astype(np.float32)
        y = _conv(lax, jnp, x, jnp.asarray(w), b, a, int(a.get("group", 1)))
        return [_qrange_clip(jnp, y, npval(i[6]), npval(i[7]),
                             npval(i[7]).dtype)]

    if op in ("QuantizeLinear", "DequantizeLinear"):
        x = val(i[0])
        if isinstance(x, np.ndarray) and x.dtype in (np.uint8, np.int8):
            # dequantizing a quantized constant
            return [_dequant_w(x, npval(i[1]),
                               npval(i[2]) if len(i) > 2 else
                               np.zeros(1, np.int64))]
        if op == "QuantizeLinear":
            zp = npval(i[2]) if len(i) > 2 else np.zeros(1, np.uint8)
            return [_qrange_clip(jnp, x, npval(i[1]), zp, zp.dtype)]
        return [x]  # float simulation: already float

    if op == "QLinearAdd":  # com.microsoft
        y = qval(0, 1, 2) + qval(3, 4, 5)
        return [_qrange_clip(jnp, y, npval(i[6]), npval(i[7]),
                             npval(i[7]).dtype)]

    if op == "QLinearMul":
        y = qval(0, 1, 2) * qval(3, 4, 5)
        return [_qrange_clip(jnp, y, npval(i[6]), npval(i[7]),
                             npval(i[7]).dtype)]

    if op == "QLinearGlobalAveragePool":
        x = qval(0, 1, 2)
        y = jnp.mean(x, axis=(2, 3), keepdims=True)
        return [_qrange_clip(jnp, y, npval(i[3]), npval(i[4]),
                             npval(i[4]).dtype)]

    if op == "QLinearMatMul":
        x = val(i[0])
        w = _dequant_w(npval(i[3]), npval(i[4]), npval(i[5]))
        y = jnp.matmul(x, jnp.asarray(w))
        return [_qrange_clip(jnp, y, npval(i[6]), npval(i[7]),
                             npval(i[7]).dtype)]

    if op == "Add":
        return [val(i[0]) + val(i[1])]
    if op == "Sub":
        return [val(i[0]) - val(i[1])]
    if op == "Mul":
        return [val(i[0]) * val(i[1])]
    if op == "Div":
        return [val(i[0]) / val(i[1])]
    if op == "Relu":
        return [jnp.maximum(val(i[0]), 0.0)]
    if op == "Sigmoid":
        return [1.0 / (1.0 + jnp.exp(-val(i[0])))]
    if op == "Tanh":
        return [jnp.tanh(val(i[0]))]
    if op == "Clip":
        lo = float(npval(i[1])) if len(i) > 1 and i[1] else \
            a.get("min", -np.inf)
        hi = float(npval(i[2])) if len(i) > 2 and i[2] else \
            a.get("max", np.inf)
        return [jnp.clip(val(i[0]), lo, hi)]
    if op == "LeakyRelu":
        alpha = a.get("alpha", 0.01)
        x = val(i[0])
        return [jnp.where(x >= 0, x, alpha * x)]
    if op == "HardSwish":
        x = val(i[0])
        return [x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)]
    if op == "HardSigmoid":
        return [jnp.clip(val(i[0]) * a.get("alpha", 0.2)
                         + a.get("beta", 0.5), 0.0, 1.0)]
    if op == "Erf":
        from jax.scipy.special import erf
        return [erf(val(i[0]))]
    if op == "Exp":
        return [jnp.exp(val(i[0]))]
    if op == "Sqrt":
        return [jnp.sqrt(val(i[0]))]
    if op == "Pow":
        return [val(i[0]) ** val(i[1])]

    if op == "GlobalAveragePool":
        return [jnp.mean(val(i[0]), axis=(2, 3), keepdims=True)]

    if op in ("MaxPool", "AveragePool"):
        x = val(i[0])
        k = tuple(a["kernel_shape"])
        strides = tuple(a.get("strides", [1] * len(k)))
        pads = a.get("pads")
        if pads and any(pads):
            n = len(pads) // 2
            pad = [(0, 0), (0, 0)] + \
                [(int(pads[d]), int(pads[d + n])) for d in range(n)]
        else:
            pad = "VALID"
        window = (1, 1) + k
        stride4 = (1, 1) + strides
        if op == "MaxPool":
            return [lax.reduce_window(x, -jnp.inf, lax.max, window,
                                      stride4, pad)]
        s = lax.reduce_window(x, 0.0, lax.add, window, stride4, pad)
        n_el = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                 stride4, pad)
        return [s / n_el]

    if op == "Gemm":
        x, w = val(i[0]), np.asarray(npval(i[1]), np.float32)
        if a.get("transB", 0):
            w = w.T
        y = (x if not a.get("transA", 0) else x.T) @ jnp.asarray(w) \
            * a.get("alpha", 1.0)
        if len(i) > 2:
            y = y + np.asarray(npval(i[2]), np.float32) * a.get("beta", 1.0)
        return [y]

    if op == "MatMul":
        return [jnp.matmul(val(i[0]), val(i[1]))]

    if op == "Reshape":
        shape = [int(d) for d in npval(i[1])]
        return [val(i[0]).reshape(shape)]
    if op == "Flatten":
        x = val(i[0])
        axis = a.get("axis", 1)
        lead = int(np.prod(x.shape[:axis])) if axis else 1
        return [x.reshape(lead, -1)]
    if op == "Transpose":
        return [jnp.transpose(val(i[0]), a.get("perm"))]
    if op == "Concat":
        return [jnp.concatenate([val(n) for n in i], axis=a["axis"])]
    if op == "Squeeze":
        axes = a.get("axes") or ([int(d) for d in npval(i[1])]
                                 if len(i) > 1 else None)
        return [jnp.squeeze(val(i[0]),
                            tuple(axes) if axes is not None else None)]
    if op == "Unsqueeze":
        axes = a.get("axes") or [int(d) for d in npval(i[1])]
        x = val(i[0])
        for ax in sorted(axes):
            x = jnp.expand_dims(x, ax)
        return [x]
    if op == "Softmax":
        x = val(i[0])
        ax = a.get("axis", -1)
        m = x.max(axis=ax, keepdims=True)
        e = jnp.exp(x - m)
        return [e / e.sum(axis=ax, keepdims=True)]
    if op == "ReduceMean":
        axes = a.get("axes") or ([int(d) for d in npval(i[1])]
                                 if len(i) > 1 else None)
        return [jnp.mean(val(i[0]),
                         axis=tuple(axes) if axes else None,
                         keepdims=bool(a.get("keepdims", 1)))]
    if op == "Shape":
        return [np.asarray(val(i[0]).shape, np.int64)]
    if op == "Gather":
        return [jnp.take(val(i[0]), val(i[1]),
                         axis=a.get("axis", 0))]
    if op == "Constant":
        return [a.get("value")]
    if op == "Identity":
        return [val(i[0])]
    if op == "Cast":
        return [val(i[0]).astype(_ELEM_NP[a["to"]])]
    if op == "Pad":
        x = val(i[0])
        pads = a.get("pads") or [int(p) for p in npval(i[1])]
        n = len(pads) // 2
        if len(i) > 3 and i[3]:  # opset-18 optional axes input
            axes = [int(ax) % x.ndim for ax in npval(i[3])]
            widths = [(0, 0)] * x.ndim
            for k, ax in enumerate(axes):
                widths[ax] = (pads[k], pads[k + n])
        else:
            widths = [(pads[d], pads[d + n]) for d in range(n)]
        mode = a.get("mode", "constant")
        if isinstance(mode, bytes):
            mode = mode.decode()
        if mode == "constant":
            cval = a.get("value", 0.0)
            if len(i) > 2 and i[2]:
                cval = float(np.asarray(npval(i[2])).reshape(-1)[0])
            return [jnp.pad(x, widths, constant_values=cval)]
        if mode in ("reflect", "edge"):
            return [jnp.pad(x, widths, mode=mode)]
        raise NotImplementedError(f"Pad mode {mode!r} unsupported")
    if op == "BatchNormalization":
        x = val(i[0])
        scale = np.asarray(npval(i[1]), np.float32)
        bias = np.asarray(npval(i[2]), np.float32)
        mean = np.asarray(npval(i[3]), np.float32)
        var = np.asarray(npval(i[4]), np.float32)
        eps = a.get("epsilon", 1e-5)
        shape = [1, -1] + [1] * (x.ndim - 2)
        return [(x - mean.reshape(shape))
                / np.sqrt(var + eps).reshape(shape)
                * scale.reshape(shape) + bias.reshape(shape)]

    raise NotImplementedError(f"onnx op {op!r} not supported")


# -- public API ------------------------------------------------------------

def _info(entries) -> TensorsInfo:
    infos = TensorsInfo()
    for name, dtype, shape in entries:
        infos.append(TensorInfo(
            name=name or None,
            type=TensorType.from_dtype(np.dtype(dtype)),
            shape=tuple(int(d) for d in shape)))
    return infos


def load(path: str) -> ONNXModel:
    nodes, inits, g_in, g_out = parse(path)
    fn = _lower(nodes, inits, g_in, g_out)
    return ONNXModel(fn=fn, input_info=_info(g_in),
                     output_info=_info(g_out), path=path)
