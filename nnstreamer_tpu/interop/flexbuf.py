"""FlexBuffers (schema-less FlatBuffers) writer + reader subset.

≙ the flexbuffers library the reference links for its flexbuf codec
subplugins (ext/nnstreamer/tensor_decoder/tensordec-flexbuf.cc,
tensor_converter/tensor_converter_flexbuf.cc). Implements the wire
format from its published rules: values are inline scalars or backward
relative offsets, type bytes are ``(type << 2) | width_code``, maps are
a values-vector plus a sorted keys-vector, and the root value + type +
width live in the last bytes of the buffer.

Subset: maps with string keys, untyped vectors, signed/unsigned ints,
floats, strings, keys, and blobs — what the tensor codec needs. The
writer always uses 32-bit slots (valid, just not minimal-width); the
reader honors per-object byte widths, so minimal-width buffers from
other producers parse too.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Union

# type ids (flexbuffers.h)
NULL, INT, UINT, FLOAT, KEY, STRING = 0, 1, 2, 3, 4, 5
MAP, VECTOR = 9, 10
VECTOR_KEY = 14
BLOB, BOOL = 25, 26

_W = 4          # slot/length byte width used by the writer
_WCODE = 2      # width code for 4 bytes


class Writer:
    def __init__(self):
        self._buf = bytearray()

    # -- leaf values ---------------------------------------------------------
    def _align(self, n: int) -> None:
        while len(self._buf) % n:
            self._buf.append(0)

    def write_key(self, s: str) -> int:
        pos = len(self._buf)
        self._buf += s.encode("utf-8") + b"\0"
        return pos

    def write_string(self, s: str) -> int:
        data = s.encode("utf-8")
        self._align(_W)
        self._buf += struct.pack("<I", len(data))
        pos = len(self._buf)
        self._buf += data + b"\0"
        return pos

    def write_blob(self, data: bytes) -> int:
        self._align(_W)
        self._buf += struct.pack("<I", len(data))
        pos = len(self._buf)
        self._buf += bytes(data)
        return pos

    # -- composites ----------------------------------------------------------
    def _write_offset_slot(self, target: int) -> None:
        slot = len(self._buf)
        self._buf += struct.pack("<I", slot - target)

    def _write_value_slot(self, v: "_Val") -> None:
        if v.inline:
            self._buf += struct.pack("<i" if v.type == INT else "<I"
                                     if v.type in (UINT, BOOL) else "<f",
                                     v.value)
        else:
            self._write_offset_slot(v.value)

    def write_vector(self, items: List["_Val"]) -> "_Val":
        self._align(_W)
        self._buf += struct.pack("<I", len(items))
        pos = len(self._buf)
        for v in items:
            self._write_value_slot(v)
        for v in items:
            self._buf.append((v.type << 2) | _WCODE)
        return _Val(VECTOR, pos, inline=False)

    def write_map(self, entries: Dict[str, "_Val"]) -> "_Val":
        # keys must be stored sorted (lookup contract of the format)
        names = sorted(entries)
        key_pos = [self.write_key(k) for k in names]
        # keys vector: typed VECTOR_KEY (length + offset slots, no types)
        self._align(_W)
        self._buf += struct.pack("<I", len(names))
        keys_vec = len(self._buf)
        for kp in key_pos:
            self._write_offset_slot(kp)
        # map: [keys_offset][keys_width][length][value slots][type bytes]
        self._align(_W)
        self._write_offset_slot(keys_vec)
        self._buf += struct.pack("<I", _W)
        self._buf += struct.pack("<I", len(names))
        pos = len(self._buf)
        for k in names:
            self._write_value_slot(entries[k])
        for k in names:
            v = entries[k]
            self._buf.append((v.type << 2) | _WCODE)
        return _Val(MAP, pos, inline=False)

    def finish(self, root: "_Val") -> bytes:
        self._align(_W)
        if root.inline:
            self._buf += struct.pack("<i" if root.type == INT else "<I",
                                     root.value)
        else:
            self._write_offset_slot(root.value)
        self._buf.append((root.type << 2) | _WCODE)
        self._buf.append(_W)
        return bytes(self._buf)


class _Val:
    """A value to be placed in a slot: inline scalar or offset."""

    __slots__ = ("type", "value", "inline")

    def __init__(self, type_: int, value, inline: bool):
        self.type, self.value, self.inline = type_, value, inline


def val_int(v: int) -> _Val:
    return _Val(INT, int(v), True)


def val_uint(v: int) -> _Val:
    return _Val(UINT, int(v), True)


# -- reader -------------------------------------------------------------------

class Ref:
    """A decoded reference into a flexbuffer.

    Two widths matter, per the format: ``slot_width`` (the parent's
    element width — how to read THIS value slot, inline scalar or
    offset) and ``byte_width`` from the packed type byte (the width of
    the referenced object's internal scalars: length prefixes, vector
    element slots).
    """

    def __init__(self, buf: bytes, pos: int, type_: int,
                 slot_width: int, byte_width: int):
        self._buf = buf
        self._pos = pos        # position of the value slot
        self._type = type_
        self._sw = slot_width
        self._bw = byte_width

    # scalar readers keyed by width
    def _read_scalar(self, pos: int, width: int, signed: bool) -> int:
        raw = self._buf[pos:pos + width]
        return int.from_bytes(raw, "little", signed=signed)

    def _indirect(self) -> int:
        return self._pos - self._read_scalar(self._pos, self._sw,
                                             signed=False)

    @property
    def type(self) -> int:
        return self._type

    def as_int(self) -> int:
        return self._read_scalar(self._pos, self._sw,
                                 signed=self._type == INT)

    def as_float(self) -> float:
        fmt = "<f" if self._sw == 4 else "<d"
        return struct.unpack_from(fmt, self._buf, self._pos)[0]

    def as_str(self) -> str:
        tgt = self._indirect()
        if self._type == KEY:
            end = self._buf.index(b"\0", tgt)
            return self._buf[tgt:end].decode("utf-8")
        n = self._read_scalar(tgt - self._bw, self._bw, signed=False)
        return self._buf[tgt:tgt + n].decode("utf-8")

    def as_blob(self) -> bytes:
        tgt = self._indirect()
        n = self._read_scalar(tgt - self._bw, self._bw, signed=False)
        return self._buf[tgt:tgt + n]

    def as_vector(self) -> List["Ref"]:
        pos = self._indirect()
        w = self._bw
        n = self._read_scalar(pos - w, w, signed=False)
        types_at = pos + n * w
        out = []
        for i in range(n):
            tb = self._buf[types_at + i]
            out.append(Ref(self._buf, pos + i * w, tb >> 2, w,
                           1 << (tb & 3)))
        return out

    def as_map(self) -> Dict[str, "Ref"]:
        pos = self._indirect()
        w = self._bw
        n = self._read_scalar(pos - w, w, signed=False)
        types_at = pos + n * w
        keys_slot = pos - 3 * w
        keys_vec = keys_slot - self._read_scalar(keys_slot, w, signed=False)
        key_w = self._read_scalar(pos - 2 * w, w, signed=False)
        out = {}
        for i in range(n):
            kslot = keys_vec + i * key_w
            ktgt = kslot - self._read_scalar(kslot, key_w, signed=False)
            kend = self._buf.index(b"\0", ktgt)
            key = self._buf[ktgt:kend].decode("utf-8")
            tb = self._buf[types_at + i]
            out[key] = Ref(self._buf, pos + i * w, tb >> 2, w,
                           1 << (tb & 3))
        return out


def root(buf: bytes) -> Ref:
    slot_width = buf[-1]
    tb = buf[-2]
    return Ref(buf, len(buf) - 2 - slot_width, tb >> 2, slot_width,
               1 << (tb & 3))
