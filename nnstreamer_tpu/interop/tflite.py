"""TFLite model importer: .tflite flatbuffer -> one jittable JAX function.

The reference runs .tflite models through the TensorFlow Lite interpreter
(ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc:1-1825,
delegates XNNPACK/GPU/NNAPI). The TPU-native equivalent is an importer:
parse the flatbuffer once at open, dequantize constants, and lower the op
graph to a pure JAX function that XLA compiles for the MXU — the model
becomes a first-class jit program instead of an interpreter call.

Quantized models run in float simulation: uint8/int8 weights dequantize at
import ((q - zero_point) * scale, per-tensor or per-axis), activations stay
float end-to-end, and graph inputs/outputs (de)quantize at the boundary so
the wire dtypes match the model's declared signature. Classification
argmax is invariant under the final affine requantization, so golden-label
parity holds (tests mirror
tests/nnstreamer_filter_tensorflow2_lite/runTest.sh:69-80).

Static shapes only — consistent with both TFLite's static tensor shapes
and XLA's compilation model.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..tensors.info import TensorInfo, TensorsInfo
from ..tensors.types import TensorType
from .flatbuf import FlatBuf

# -- schema enums (tensorflow/lite/schema/schema.fbs) ----------------------

_TENSOR_NP = {0: np.float32, 1: np.float16, 2: np.int32, 3: np.uint8,
              4: np.int64, 6: np.bool_, 7: np.int16, 9: np.int8,
              10: np.float64}

# BuiltinOperator values used below
ADD, AVERAGE_POOL_2D, CONCATENATION, CONV_2D, DEPTHWISE_CONV_2D = 0, 1, 2, 3, 4
DEQUANTIZE, FULLY_CONNECTED, LOGISTIC, MAX_POOL_2D, MUL = 6, 9, 14, 17, 18
RELU, RELU6, RESHAPE, RESIZE_BILINEAR, SOFTMAX, TANH = 19, 21, 22, 23, 25, 28
PAD, TRANSPOSE, MEAN, SUB, DIV, SQUEEZE, STRIDED_SLICE = 34, 39, 40, 41, 42, 43, 45
EXP, LOG_SOFTMAX, CAST, PRELU, MAXIMUM, ARG_MAX, MINIMUM = 47, 50, 53, 54, 55, 56, 57
SLICE, TRANSPOSE_CONV, EXPAND_DIMS, SUM, SHAPE, POW = 65, 67, 70, 74, 77, 78
PACK, LEAKY_RELU, SQUARED_DIFFERENCE, ABS = 83, 98, 99, 101
RESIZE_NEAREST_NEIGHBOR = 97
QUANTIZE, HARD_SWISH = 114, 117
BATCH_MATMUL = 126
BROADCAST_TO, BROADCAST_ARGS = 130, 145

_OP_NAMES = {v: k for k, v in list(globals().items())
             if isinstance(v, int) and k.isupper()}


@dataclasses.dataclass
class _Tensor:
    index: int
    name: str
    shape: Tuple[int, ...]
    dtype: Any                       # numpy dtype class
    scale: Optional[np.ndarray]      # quant scale(s) or None
    zero_point: Optional[np.ndarray]
    quant_axis: int
    const: Optional[np.ndarray]      # raw constant data (un-dequantized)

    @property
    def quantized(self) -> bool:
        return self.scale is not None and self.scale.size > 0 and \
            self.dtype in (np.uint8, np.int8, np.int32, np.int16)


@dataclasses.dataclass
class _Op:
    code: int
    inputs: List[int]
    outputs: List[int]
    options: Optional[int]           # table position in the flatbuffer
    fb: FlatBuf


@dataclasses.dataclass
class TFLiteModel:
    """Parsed model: jittable ``fn(*inputs) -> list[outputs]`` plus the
    tensor signature in framework terms."""

    fn: Callable
    input_info: TensorsInfo
    output_info: TensorsInfo
    path: str


# -- parsing ---------------------------------------------------------------

def _parse_tensor(fb: FlatBuf, pos: int, index: int,
                  buffers: List[Optional[np.ndarray]]) -> _Tensor:
    shape = fb.field_np(pos, 0, np.int32)
    shape = () if shape is None else tuple(int(d) for d in shape)
    ttype = fb.field_scalar(pos, 1, "u8")
    if ttype not in _TENSOR_NP:
        raise NotImplementedError(f"tflite tensor type {ttype} unsupported")
    dtype = _TENSOR_NP[ttype]
    buf_idx = fb.field_scalar(pos, 2, "u32")
    name = fb.field_string(pos, 3)
    scale = zero = None
    qaxis = 0
    q = fb.field_table(pos, 4)
    if q is not None:
        scale = fb.field_np(q, 2, np.float32)
        zero = fb.field_np(q, 3, np.int64)
        qaxis = fb.field_scalar(q, 6, "i32", default=0)
    raw = buffers[buf_idx] if buf_idx < len(buffers) else None
    const = None
    if raw is not None and raw.size:
        const = raw.view(dtype)[:int(np.prod(shape, dtype=np.int64))] \
            .reshape(shape)
    return _Tensor(index, name, shape, dtype, scale, zero, qaxis, const)


def parse(path: str) -> Tuple[List[_Tensor], List[_Op],
                              List[int], List[int]]:
    """Parse subgraph 0 into tensors / ops / input / output index lists."""
    with open(path, "rb") as f:
        data = f.read()
    fb = FlatBuf(data)
    root = fb.root()
    # buffers (Model field 4): raw little-endian bytes per buffer
    buffers: List[Optional[np.ndarray]] = []
    bvec = fb.field_vector(root, 4)
    if bvec is not None:
        for bpos in fb.vector_tables(bvec):
            d = fb.field_np(bpos, 0, np.uint8)
            buffers.append(d)
    # operator codes (Model field 1); builtin_code (3) supersedes the
    # deprecated int8 field 0 for codes > 127
    codes: List[int] = []
    for cpos in fb.vector_tables(fb.field_vector(root, 1)):
        dep = fb.field_scalar(cpos, 0, "i8")
        builtin = fb.field_scalar(cpos, 3, "i32", default=0)
        codes.append(builtin if builtin != 0 else dep)
    sg = next(fb.vector_tables(fb.field_vector(root, 2)))
    tensors = [
        _parse_tensor(fb, tpos, i, buffers)
        for i, tpos in enumerate(fb.vector_tables(fb.field_vector(sg, 0)))]
    inputs = [int(i) for i in fb.field_np(sg, 1, np.int32)]
    outputs = [int(i) for i in fb.field_np(sg, 2, np.int32)]
    ops: List[_Op] = []
    for opos in fb.vector_tables(fb.field_vector(sg, 3)):
        idx = fb.field_scalar(opos, 0, "u32")
        op_inputs = [int(i) for i in fb.field_np(opos, 1, np.int32)]
        op_outputs = [int(i) for i in fb.field_np(opos, 2, np.int32)]
        options = fb.field_table(opos, 4)
        ops.append(_Op(codes[idx], op_inputs, op_outputs, options, fb))
    return tensors, ops, inputs, outputs


# -- dequantization --------------------------------------------------------

def _dequantize_const(t: _Tensor) -> np.ndarray:
    """Constant to float32, applying (q - zp) * scale (per-axis aware)."""
    data = t.const
    assert data is not None
    if t.dtype in (np.float32, np.float64, np.float16):
        return data.astype(np.float32)
    if not t.quantized:
        return data  # int32 shape/axis constants stay integer
    scale = t.scale.astype(np.float64)
    zp = (t.zero_point if t.zero_point is not None
          else np.zeros_like(scale)).astype(np.float64)
    if scale.size == 1:
        return ((data.astype(np.float64) - zp[0]) * scale[0]) \
            .astype(np.float32)
    bshape = [1] * data.ndim
    bshape[t.quant_axis] = scale.size
    return ((data.astype(np.float64) - zp.reshape(bshape))
            * scale.reshape(bshape)).astype(np.float32)


# -- lowering --------------------------------------------------------------

_ACT = {0: None, 1: "relu", 2: "relu_n1_to_1", 3: "relu6", 4: "tanh"}


def _apply_act(jnp, x, act_code: int):
    act = _ACT.get(act_code)
    if act is None:
        return x
    if act == "relu":
        return jnp.maximum(x, 0.0)
    if act == "relu6":
        return jnp.clip(x, 0.0, 6.0)
    if act == "relu_n1_to_1":
        return jnp.clip(x, -1.0, 1.0)
    return jnp.tanh(x)


def _pool_avg(lax, jnp, x, ksize, strides, padding):
    ones = jnp.ones_like(x)
    window = (1, ksize[0], ksize[1], 1)
    strides4 = (1, strides[0], strides[1], 1)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides4, padding)
    # average over VALID window elements only (TFLite SAME-pad semantics)
    n = lax.reduce_window(ones, 0.0, lax.add, window, strides4, padding)
    return s / n


def _bilinear(jnp, x, out_h, out_w, align_corners, half_pixel):
    n, in_h, in_w, c = x.shape

    def coords(out, inp):
        idx = jnp.arange(out, dtype=jnp.float32)
        if align_corners and out > 1:
            return idx * ((inp - 1) / (out - 1))
        if half_pixel:
            return jnp.maximum((idx + 0.5) * (inp / out) - 0.5, 0.0)
        return idx * (inp / out)

    ys, xs = coords(out_h, in_h), coords(out_w, in_w)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, in_h - 1)
    y1 = jnp.clip(y0 + 1, 0, in_h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, in_w - 1)
    x1 = jnp.clip(x0 + 1, 0, in_w - 1)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    top = jnp.take(x, y0, axis=1)
    bot = jnp.take(x, y1, axis=1)
    tl, tr = jnp.take(top, x0, axis=2), jnp.take(top, x1, axis=2)
    bl, br = jnp.take(bot, x0, axis=2), jnp.take(bot, x1, axis=2)
    t = tl + (tr - tl) * wx
    b = bl + (br - bl) * wx
    return t + (b - t) * wy


def _lower(tensors: List[_Tensor], ops: List[_Op],
           graph_in: List[int], graph_out: List[int]) -> Callable:
    """Build fn(*inputs)->list[outputs]. Constants (dequantized) are
    closed over; inside jit XLA hoists them to device constants."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    consts: Dict[int, np.ndarray] = {
        t.index: _dequantize_const(t) for t in tensors
        if t.const is not None}
    for t in tensors:
        # drop the raw quantized views: fn closes over `tensors` only for
        # shape/quant metadata, keeping both copies would double-retain
        # the weights for the model's lifetime
        t.const = None

    def fn(*args):
        env: Dict[int, Any] = {}
        for i, gi in enumerate(graph_in):
            t = tensors[gi]
            x = args[i]
            if t.quantized and t.dtype in (np.uint8, np.int8):
                # boundary dequantize: wire dtype -> float simulation
                x = (x.astype(jnp.float32) - float(t.zero_point[0])) \
                    * float(t.scale[0])
            elif x.dtype != jnp.float32 and t.dtype == np.float32:
                x = x.astype(jnp.float32)
            env[gi] = x

        def val(idx: int):
            if idx in env:
                return env[idx]
            if idx in consts:
                return consts[idx]
            raise KeyError(
                f"tensor {idx} used before produced "
                f"({tensors[idx].name!r})")

        def const_val(idx: int) -> np.ndarray:
            if idx in consts:
                return consts[idx]
            v = env.get(idx)
            if isinstance(v, np.ndarray):
                return v
            raise NotImplementedError(
                f"op needs compile-time constant for tensor {idx} "
                f"({tensors[idx].name!r})")

        for op in ops:
            y = _eval_op(op, val, const_val, tensors, jnp, lax)
            t = tensors[op.outputs[0]]
            if t.quantized and t.dtype in (np.uint8, np.int8) and \
                    t.scale.size == 1:
                # quantized storage saturates activations to the tensor's
                # representable range — the float simulation must too, or
                # deep nets drift (this is also how TFLite bakes ReLU6
                # into quant ranges instead of explicit activation ops)
                info = np.iinfo(t.dtype)
                zp = float(t.zero_point[0]) if t.zero_point is not None \
                    else 0.0
                s = float(t.scale[0])
                y = jnp.clip(y, (info.min - zp) * s, (info.max - zp) * s)
            env[op.outputs[0]] = y

        outs = []
        for go in graph_out:
            y = val(go)
            t = tensors[go]
            if t.quantized and t.dtype in (np.uint8, np.int8):
                # boundary requantize back to the declared wire dtype
                info = np.iinfo(t.dtype)
                q = jnp.round(y / float(t.scale[0])) + float(t.zero_point[0])
                y = jnp.clip(q, info.min, info.max).astype(t.dtype)
            outs.append(y)
        return outs

    return fn


def _eval_op(op: _Op, val, const_val, tensors, jnp, lax):
    fb, opt = op.fb, op.options
    code = op.code

    def scalar(fid, kind, default=0):
        if opt is None:
            return default
        return fb.field_scalar(opt, fid, kind, default=default)

    if code == CONV_2D:
        x = val(op.inputs[0])
        w = const_val(op.inputs[1])           # OHWI
        padding = "SAME" if scalar(0, "i8") == 0 else "VALID"
        strides = (scalar(2, "i32", 1), scalar(1, "i32", 1))  # (h, w)
        dil = (scalar(5, "i32", 1) or 1, scalar(4, "i32", 1) or 1)
        y = lax.conv_general_dilated(
            x, jnp.asarray(np.transpose(w, (1, 2, 3, 0))),  # -> HWIO
            window_strides=strides, padding=padding, rhs_dilation=dil,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + const_val(op.inputs[2])
        return _apply_act(jnp, y, scalar(3, "i8"))

    if code == DEPTHWISE_CONV_2D:
        x = val(op.inputs[0])
        w = const_val(op.inputs[1])           # [1, kh, kw, in*mult]
        padding = "SAME" if scalar(0, "i8") == 0 else "VALID"
        strides = (scalar(2, "i32", 1), scalar(1, "i32", 1))
        dil = (scalar(6, "i32", 1) or 1, scalar(5, "i32", 1) or 1)
        in_ch = x.shape[-1]
        y = lax.conv_general_dilated(
            x, jnp.asarray(np.transpose(w, (1, 2, 0, 3))),  # -> HW1(in*mult)
            window_strides=strides, padding=padding, rhs_dilation=dil,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=in_ch,
            preferred_element_type=jnp.float32)
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + const_val(op.inputs[2])
        return _apply_act(jnp, y, scalar(4, "i8"))

    if code == FULLY_CONNECTED:
        x = val(op.inputs[0])
        w = const_val(op.inputs[1])           # [out, in]
        if x.ndim > 2 and not scalar(2, "i8"):
            x = x.reshape(-1, w.shape[1])
        y = x @ jnp.asarray(w).T
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + const_val(op.inputs[2])
        return _apply_act(jnp, y, scalar(0, "i8"))

    if code in (ADD, SUB, MUL, DIV, MAXIMUM, MINIMUM, POW,
                SQUARED_DIFFERENCE):
        a, b = val(op.inputs[0]), val(op.inputs[1])
        if code == ADD:
            y = a + b
        elif code == SUB:
            y = a - b
        elif code == MUL:
            y = a * b
        elif code == DIV:
            y = a / b
        elif code == MAXIMUM:
            y = jnp.maximum(a, b)
        elif code == MINIMUM:
            y = jnp.minimum(a, b)
        elif code == POW:
            y = a ** b
        else:
            y = (a - b) ** 2
        # ADD/SUB/MUL/DIV carry a fused activation at options field 0
        if code in (ADD, SUB, MUL, DIV):
            y = _apply_act(jnp, y, scalar(0, "i8"))
        return y

    if code in (AVERAGE_POOL_2D, MAX_POOL_2D):
        x = val(op.inputs[0])
        padding = "SAME" if scalar(0, "i8") == 0 else "VALID"
        strides = (scalar(2, "i32", 1), scalar(1, "i32", 1))
        ksize = (scalar(4, "i32", 1), scalar(3, "i32", 1))
        if code == AVERAGE_POOL_2D:
            y = _pool_avg(lax, jnp, x, ksize, strides, padding)
        else:
            y = lax.reduce_window(
                x, -jnp.inf, lax.max, (1, *ksize, 1), (1, *strides, 1),
                padding)
        return _apply_act(jnp, y, scalar(5, "i8"))

    if code == RESHAPE:
        x = val(op.inputs[0])
        if opt is not None and fb.field_vector(opt, 0) is not None:
            shape = [int(d) for d in fb.field_np(opt, 0, np.int32)]
        else:
            shape = [int(d) for d in const_val(op.inputs[1])]
        return x.reshape(shape)

    if code == SQUEEZE:
        x = val(op.inputs[0])
        dims = (fb.field_np(opt, 0, np.int32)
                if opt is not None else None)
        if dims is None or len(dims) == 0:
            return jnp.squeeze(x)
        return jnp.squeeze(x, axis=tuple(int(d) for d in dims))

    if code == EXPAND_DIMS:
        return jnp.expand_dims(val(op.inputs[0]),
                               int(const_val(op.inputs[1])))

    if code == SOFTMAX:
        beta = scalar(0, "f32", 1.0) or 1.0
        return jax_softmax(jnp, val(op.inputs[0]) * beta)

    if code == LOG_SOFTMAX:
        x = val(op.inputs[0])
        return x - jnp.log(jnp.sum(jnp.exp(x - x.max(-1, keepdims=True)),
                                   -1, keepdims=True)) \
            - x.max(-1, keepdims=True)

    if code == CONCATENATION:
        axis = scalar(0, "i32")
        parts = [val(i) for i in op.inputs]
        return _apply_act(jnp, jnp.concatenate(parts, axis=axis),
                          scalar(1, "i8"))

    if code in (RESIZE_BILINEAR, RESIZE_NEAREST_NEIGHBOR):
        x = val(op.inputs[0])
        out_h, out_w = (int(d) for d in const_val(op.inputs[1]))
        align = bool(scalar(2, "u8"))
        half = bool(scalar(3, "u8"))
        if code == RESIZE_BILINEAR:
            return _bilinear(jnp, x, out_h, out_w, align, half)
        method = "nearest"
        import jax.image as jimage
        return jimage.resize(x, (x.shape[0], out_h, out_w, x.shape[3]),
                             method=method)

    if code == PAD:
        x = val(op.inputs[0])
        pads = const_val(op.inputs[1]).astype(int)
        return jnp.pad(x, [(int(a), int(b)) for a, b in pads])

    if code in (MEAN, SUM):
        x = val(op.inputs[0])
        axes = tuple(int(a) for a in np.atleast_1d(const_val(op.inputs[1])))
        keep = bool(scalar(0, "u8"))
        red = jnp.mean if code == MEAN else jnp.sum
        return red(x, axis=axes, keepdims=keep)

    if code == TRANSPOSE:
        perm = [int(p) for p in const_val(op.inputs[1])]
        return jnp.transpose(val(op.inputs[0]), perm)

    if code == RELU:
        return jnp.maximum(val(op.inputs[0]), 0.0)
    if code == RELU6:
        return jnp.clip(val(op.inputs[0]), 0.0, 6.0)
    if code == LOGISTIC:
        return 1.0 / (1.0 + jnp.exp(-val(op.inputs[0])))
    if code == TANH:
        return jnp.tanh(val(op.inputs[0]))
    if code == HARD_SWISH:
        x = val(op.inputs[0])
        return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0
    if code == LEAKY_RELU:
        alpha = scalar(0, "f32", 0.01)
        x = val(op.inputs[0])
        return jnp.where(x >= 0, x, alpha * x)
    if code == PRELU:
        x, a = val(op.inputs[0]), val(op.inputs[1])
        return jnp.where(x >= 0, x, a * x)
    if code == ABS:
        return jnp.abs(val(op.inputs[0]))
    if code == EXP:
        return jnp.exp(val(op.inputs[0]))

    if code == ARG_MAX:
        axis = int(const_val(op.inputs[1]))
        out_t = tensors[op.outputs[0]].dtype
        return jnp.argmax(val(op.inputs[0]), axis=axis).astype(out_t)

    if code == CAST:
        return val(op.inputs[0]).astype(tensors[op.outputs[0]].dtype)

    if code in (DEQUANTIZE, QUANTIZE):
        # float simulation: activations are already float end-to-end
        return val(op.inputs[0])

    if code == SHAPE:
        return np.asarray(tensors[op.inputs[0]].shape
                          if tensors[op.inputs[0]].shape
                          else val(op.inputs[0]).shape,
                          tensors[op.outputs[0]].dtype)

    if code == BROADCAST_ARGS:
        a = const_val(op.inputs[0])
        b = const_val(op.inputs[1])
        return np.asarray(
            np.broadcast_shapes(tuple(int(x) for x in a),
                                tuple(int(x) for x in b)),
            tensors[op.outputs[0]].dtype)

    if code == BROADCAST_TO:
        shape = [int(d) for d in const_val(op.inputs[1])]
        return jnp.broadcast_to(val(op.inputs[0]), shape)

    if code == PACK:
        axis = scalar(1, "i32")
        return jnp.stack([val(i) for i in op.inputs], axis=axis)

    if code == SLICE:
        x = val(op.inputs[0])
        begin = [int(b) for b in const_val(op.inputs[1])]
        size = [int(s) for s in const_val(op.inputs[2])]
        idx = tuple(slice(b, x.shape[d] if s == -1 else b + s)
                    for d, (b, s) in enumerate(zip(begin, size)))
        return x[idx]

    if code == STRIDED_SLICE:
        x = val(op.inputs[0])
        begin = [int(b) for b in const_val(op.inputs[1])]
        end = [int(e) for e in const_val(op.inputs[2])]
        strides = [int(s) for s in const_val(op.inputs[3])]
        bm = scalar(0, "i32")
        em = scalar(1, "i32")
        ellipsis = scalar(2, "i32")
        new_axis = scalar(3, "i32")
        shrink = scalar(4, "i32")
        if ellipsis or new_axis:
            raise NotImplementedError(
                "STRIDED_SLICE ellipsis_mask/new_axis_mask unsupported")
        idx = []
        for d in range(len(begin)):
            if shrink & (1 << d):
                idx.append(begin[d])
                continue
            b = None if bm & (1 << d) else begin[d]
            e = None if em & (1 << d) else end[d]
            idx.append(slice(b, e, strides[d]))
        return x[tuple(idx)]

    if code == BATCH_MATMUL:
        a, b = val(op.inputs[0]), val(op.inputs[1])
        adj_x = bool(scalar(0, "u8"))
        adj_y = bool(scalar(1, "u8"))
        if adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    if code == TRANSPOSE_CONV:
        out_shape = [int(d) for d in const_val(op.inputs[0])]
        w = const_val(op.inputs[1])           # OHWI
        x = val(op.inputs[2])
        padding = "SAME" if scalar(0, "i8") == 0 else "VALID"
        strides = (scalar(2, "i32", 1), scalar(1, "i32", 1))
        y = lax.conv_transpose(
            x, jnp.asarray(np.transpose(w, (1, 2, 3, 0))),
            strides, padding, dimension_numbers=("NHWC", "HWIO", "NHWC"),
            transpose_kernel=True)
        if len(op.inputs) > 3 and op.inputs[3] >= 0:
            y = y + const_val(op.inputs[3])
        return y[:, :out_shape[1], :out_shape[2], :]

    raise NotImplementedError(
        f"tflite op {_OP_NAMES.get(code, code)} ({code}) not supported")


def jax_softmax(jnp, x):
    m = x.max(axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    return e / e.sum(axis=-1, keepdims=True)


# -- public API ------------------------------------------------------------

def _info_of(tensors: List[_Tensor], indices: List[int]) -> TensorsInfo:
    infos = TensorsInfo()
    for i in indices:
        t = tensors[i]
        infos.append(TensorInfo(
            name=t.name or None,
            type=TensorType.from_dtype(np.dtype(t.dtype)),
            shape=tuple(t.shape)))
    return infos


def load(path: str) -> TFLiteModel:
    """Parse + lower a .tflite file to a jittable function."""
    tensors, ops, graph_in, graph_out = parse(path)
    fn = _lower(tensors, ops, graph_in, graph_out)
    return TFLiteModel(
        fn=fn,
        input_info=_info_of(tensors, graph_in),
        output_info=_info_of(tensors, graph_out),
        path=path)
