"""Minimal protobuf wire-format codec — no schema compiler, no deps.

Decodes a message into ``{field_number: [values]}`` where values are ints
(varint/fixed), floats (when asked), or bytes (length-delimited; nested
messages decode by calling :func:`decode` again on the bytes). Encoding
helpers build messages field-by-field. Enough for walking ONNX models
(interop/onnx.py) and for the protobuf tensor codec
(≙ ext/nnstreamer/extra/nnstreamer_protobuf.cc, which links libprotobuf).
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

Value = Union[int, bytes]


def read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, Value]]:
    """Yield (field_number, wire_type, value). Length-delimited values are
    bytes; varint/fixed values are ints (reinterpret as needed)."""
    buf = memoryview(data)
    pos = 0
    end = len(buf)
    while pos < end:
        key, pos = read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = read_varint(buf, pos)
            yield field, wt, v
        elif wt == 1:
            yield field, wt, struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == 2:
            n, pos = read_varint(buf, pos)
            yield field, wt, bytes(buf[pos:pos + n])
            pos += n
        elif wt == 5:
            yield field, wt, struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")


def decode(data: bytes) -> Dict[int, List[Value]]:
    out: Dict[int, List[Value]] = {}
    for field, _, v in iter_fields(data):
        out.setdefault(field, []).append(v)
    return out


# -- typed readers ---------------------------------------------------------

def as_f32(v: int) -> float:
    return struct.unpack("<f", struct.pack("<I", v & 0xFFFFFFFF))[0]


def as_f64(v: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", v))[0]


def as_sint(v: int) -> int:
    """Two's-complement reinterpretation of a varint read as unsigned
    (proto int64/int32 negative values)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def packed_varints(data: bytes) -> List[int]:
    buf = memoryview(data)
    pos, out = 0, []
    while pos < len(buf):
        v, pos = read_varint(buf, pos)
        out.append(v)
    return out


# -- encoding --------------------------------------------------------------

def enc_varint(value: int) -> bytes:
    out = bytearray()
    if value < 0:
        value += 1 << 64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def enc_tag(field: int, wire_type: int) -> bytes:
    return enc_varint((field << 3) | wire_type)


def enc_int(field: int, value: int) -> bytes:
    return enc_tag(field, 0) + enc_varint(value)


def enc_bytes(field: int, value: bytes) -> bytes:
    return enc_tag(field, 2) + enc_varint(len(value)) + value


def enc_str(field: int, value: str) -> bytes:
    return enc_bytes(field, value.encode("utf-8"))


def enc_f32(field: int, value: float) -> bytes:
    return enc_tag(field, 5) + struct.pack("<f", value)


def enc_f64(field: int, value: float) -> bytes:
    return enc_tag(field, 1) + struct.pack("<d", value)
