"""Tensor-stream wire codecs: flatbuf / protobuf / flexbuf / octet.

Serializes a tensor frame (list of ndarrays + stream config) to the
reference's interchange formats and back:

  * flatbuf  — the ``nnstreamer.flatbuf.Tensors`` schema
               (ref: ext/nnstreamer/include/nnstreamer.fbs)
  * protobuf — the ``nnstreamer.protobuf.Tensors`` message
               (ref: ext/nnstreamer/include/nnstreamer.proto)
  * flexbuf  — the schema-less map layout documented in
               ref: ext/nnstreamer/tensor_decoder/tensordec-flexbuf.cc:26-35
  * octet    — raw concatenated tensor bytes

Dimensions are serialized in the reference's innermost-first order,
zero-padded to rank 16 (≙ NNS_TENSOR_RANK_LIMIT).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..tensors.types import TensorType
from . import flexbuf
from .flatbuild import Builder
from .flatbuf import FlatBuf
from .protowire import (decode, enc_bytes, enc_int, enc_str, enc_varint,
                        packed_varints)

RANK_LIMIT = 16


class Frame:
    """One decoded tensor frame."""

    def __init__(self, arrays: List[np.ndarray],
                 names: Optional[List[str]] = None,
                 rate_n: int = 0, rate_d: int = 1, fmt: int = 0):
        self.arrays = arrays
        self.names = names or [""] * len(arrays)
        self.rate_n, self.rate_d, self.fmt = rate_n, rate_d, fmt


def _ref_dims(arr: np.ndarray) -> List[int]:
    dims = [int(d) for d in reversed(arr.shape)] or [1]
    return dims + [0] * (RANK_LIMIT - len(dims))


def _shape_from_ref_dims(dims: List[int]) -> Tuple[int, ...]:
    trimmed = [d for d in dims if d > 0]
    return tuple(reversed(trimmed)) if trimmed else (1,)


def _np_dtype(nns_type: int):
    return TensorType(nns_type).np_dtype


# -- flatbuf -------------------------------------------------------------------

def pack_flatbuf(frame: Frame) -> bytes:
    b = Builder()
    tensor_offs = []
    for name, arr in zip(frame.names, frame.arrays):
        name_off = b.create_string(name or "")
        data_off = b.create_vector_u8(np.ascontiguousarray(arr).tobytes())
        dim_off = b.create_vector_u32(_ref_dims(arr))
        b.start_table()
        b.add_offset(0, name_off)                     # name
        b.add_scalar(1, "i", int(TensorType.from_dtype(arr.dtype)),
                     default=11)                      # type (default NNS_END)
        b.add_offset(2, dim_off)                      # dimension
        b.add_offset(3, data_off)                     # data
        tensor_offs.append(b.end_table())
    vec_off = b.create_vector_offsets(tensor_offs)
    b.start_table()
    b.add_scalar(0, "i", len(frame.arrays))           # num_tensor
    import struct
    b.add_struct(1, struct.pack("<ii", frame.rate_n, frame.rate_d))  # fr
    b.add_offset(2, vec_off)                          # tensor
    b.add_scalar(3, "i", frame.fmt)                   # format
    return b.finish(b.end_table())


def unpack_flatbuf(data: bytes) -> Frame:
    fb = FlatBuf(data)
    root = fb.root()
    fr_pos = fb.field(root, 1)
    rate_n = fb.i32(fr_pos) if fr_pos is not None else 0
    rate_d = fb.i32(fr_pos + 4) if fr_pos is not None else 1
    fmt = fb.field_scalar(root, 3, "i32", 0)
    arrays, names = [], []
    vec = fb.field_vector(root, 2)
    if vec is not None:
        for t in fb.vector_tables(vec):
            names.append(fb.field_string(t, 0))
            ttype = fb.field_scalar(t, 1, "i32", 11)
            dims = fb.field_np(t, 2, np.uint32)
            raw = fb.field_np(t, 3, np.uint8)
            shape = _shape_from_ref_dims(list(dims) if dims is not None
                                         else [])
            arr = np.frombuffer(
                raw.tobytes() if raw is not None else b"",
                dtype=_np_dtype(ttype)).reshape(shape)
            arrays.append(arr)
    return Frame(arrays, names, rate_n, rate_d, fmt)


# -- protobuf ------------------------------------------------------------------

def pack_protobuf(frame: Frame) -> bytes:
    out = bytearray()
    out += enc_int(1, len(frame.arrays))                       # num_tensor
    fr = enc_int(1, frame.rate_n) + enc_int(2, frame.rate_d)
    out += enc_bytes(2, fr)                                    # fr message
    for name, arr in zip(frame.names, frame.arrays):
        t = bytearray()
        if name:
            t += enc_str(1, name)
        t += enc_int(2, int(TensorType.from_dtype(arr.dtype)))
        dims = b"".join(enc_varint(d) for d in _ref_dims(arr))
        t += enc_bytes(3, dims)                                # packed dims
        t += enc_bytes(4, np.ascontiguousarray(arr).tobytes())
        out += enc_bytes(3, bytes(t))                          # Tensor
    out += enc_int(4, frame.fmt)                               # format
    return bytes(out)


def unpack_protobuf(data: bytes) -> Frame:
    top = decode(data)
    fr = decode(top.get(2, [b""])[0]) if 2 in top else {}
    rate_n = int(fr.get(1, [0])[0])
    rate_d = int(fr.get(2, [1])[0])
    fmt = int(top.get(4, [0])[0])
    arrays, names = [], []
    for tbytes in top.get(3, []):
        t = decode(tbytes)
        name = t.get(1, [b""])[0]
        names.append(name.decode() if isinstance(name, bytes) else "")
        ttype = int(t.get(2, [0])[0])
        dims = packed_varints(t.get(3, [b""])[0])
        raw = t.get(4, [b""])[0]
        arr = np.frombuffer(raw, dtype=_np_dtype(ttype)).reshape(
            _shape_from_ref_dims(dims))
        arrays.append(arr)
    return Frame(arrays, names, rate_n, rate_d, fmt)


# -- flexbuf -------------------------------------------------------------------

def pack_flexbuf(frame: Frame) -> bytes:
    w = flexbuf.Writer()
    entries = {}
    for i, (name, arr) in enumerate(zip(frame.names, frame.arrays)):
        name_off = w.write_string(name or "")
        dims = w.write_vector([flexbuf.val_uint(d) for d in _ref_dims(arr)])
        blob = w.write_blob(np.ascontiguousarray(arr).tobytes())
        vec = w.write_vector([
            flexbuf._Val(flexbuf.STRING, name_off, inline=False),
            flexbuf.val_int(int(TensorType.from_dtype(arr.dtype))),
            dims,
            flexbuf._Val(flexbuf.BLOB, blob, inline=False),
        ])
        entries[f"tensor_{i}"] = vec
    entries["num_tensors"] = flexbuf.val_uint(len(frame.arrays))
    entries["rate_n"] = flexbuf.val_int(frame.rate_n)
    entries["rate_d"] = flexbuf.val_int(frame.rate_d)
    entries["format"] = flexbuf.val_int(frame.fmt)
    return w.finish(w.write_map(entries))


def unpack_flexbuf(data: bytes) -> Frame:
    m = flexbuf.root(data).as_map()
    n = m["num_tensors"].as_int()
    rate_n = m["rate_n"].as_int()
    rate_d = m["rate_d"].as_int()
    fmt = m["format"].as_int() if "format" in m else 0
    arrays, names = [], []
    for i in range(n):
        item = m[f"tensor_{i}"].as_vector()
        names.append(item[0].as_str())
        ttype = item[1].as_int()
        dims = [r.as_int() for r in item[2].as_vector()]
        raw = item[3].as_blob()
        arrays.append(np.frombuffer(bytes(raw), dtype=_np_dtype(ttype))
                      .reshape(_shape_from_ref_dims(dims)))
    return Frame(arrays, names, rate_n, rate_d, fmt)


# -- octet ---------------------------------------------------------------------

def pack_octet(frame: Frame) -> bytes:
    return b"".join(np.ascontiguousarray(a).tobytes() for a in frame.arrays)
