"""ctypes bindings for libnnstpu.so (csrc/).

Build with ``make native`` at the repo root; ``load_native_lib`` also
triggers a build on demand when a toolchain is present so a fresh checkout
works without a manual step. Everything here degrades gracefully: callers
check :func:`native_available` and fall back to the pure-Python paths.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from ..utils.log import logger

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_LIB_PATH = os.path.join(_REPO_ROOT, "build", "native", "libnnstpu.so")

_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None
_tried = False

RANK_LIMIT = 16
TENSOR_LIMIT = 16


class NnsTensorInfo(ctypes.Structure):
    _fields_ = [("rank", ctypes.c_uint32),
                ("dims", ctypes.c_uint32 * RANK_LIMIT),
                ("type", ctypes.c_int32)]


class NnsTensorsInfo(ctypes.Structure):
    _fields_ = [("num", ctypes.c_uint32),
                ("info", NnsTensorInfo * TENSOR_LIMIT)]


def _try_build() -> bool:
    makefile = os.path.join(_REPO_ROOT, "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(["make", "-C", _REPO_ROOT, "native"], check=True,
                       capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.info("native build unavailable: %s", e)
        return False


def native_built() -> bool:
    """True when libnnstpu.so is already on disk — the cheap probe for
    opportunistic callers that must NOT trigger an on-demand build."""
    return os.path.exists(_LIB_PATH)


def load_native_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # always run make: its dependency tracking makes this a no-op when
        # the .so is fresh, and rebuilds after any csrc/ change so a stale
        # binary is never silently loaded over newer source
        if not _try_build() and not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.warning("cannot load %s: %s", _LIB_PATH, e)
            return None
        lib.nns_parse_dimension.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32)]
        lib.nns_parse_dimension.restype = ctypes.c_int
        lib.nns_serialize_dimension.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_size_t]
        lib.nns_serialize_dimension.restype = ctypes.c_int
        lib.nns_element_size.argtypes = [ctypes.c_int32]
        lib.nns_element_size.restype = ctypes.c_size_t
        lib.nns_infos_are_equal.argtypes = [
            ctypes.POINTER(NnsTensorsInfo), ctypes.POINTER(NnsTensorsInfo)]
        lib.nns_infos_are_equal.restype = ctypes.c_int
        lib.nns_ring_new.argtypes = [ctypes.c_uint32]
        lib.nns_ring_new.restype = ctypes.c_void_p
        lib.nns_ring_free.argtypes = [ctypes.c_void_p]
        lib.nns_ring_close.argtypes = [ctypes.c_void_p]
        lib.nns_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int64]
        lib.nns_ring_push.restype = ctypes.c_int
        lib.nns_ring_pop.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.c_int64]
        lib.nns_ring_pop.restype = ctypes.c_int
        lib.nns_ring_size.argtypes = [ctypes.c_void_p]
        lib.nns_ring_size.restype = ctypes.c_uint32
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_native_lib() is not None


class NativeRing:
    """Bounded queue backed by the C++ ring; holds python objects alive
    while their ids transit the native queue."""

    def __init__(self, capacity: int):
        lib = load_native_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._ring = lib.nns_ring_new(capacity)
        self._refs = {}
        self._refs_lock = threading.Lock()
        self._next_id = [1]

    def push(self, item, timeout_ms: int = -1) -> bool:
        with self._refs_lock:
            key = self._next_id[0]
            self._next_id[0] += 1
            self._refs[key] = item
        rc = self._lib.nns_ring_push(self._ring, ctypes.c_void_p(key),
                                     timeout_ms)
        if rc != 0:
            with self._refs_lock:
                self._refs.pop(key, None)
        return rc == 0

    def pop(self, timeout_ms: int = -1):
        out = ctypes.c_void_p()
        rc = self._lib.nns_ring_pop(self._ring, ctypes.byref(out), timeout_ms)
        if rc != 0:
            return None
        with self._refs_lock:
            return self._refs.pop(out.value)

    def close(self) -> None:
        self._lib.nns_ring_close(self._ring)

    def __len__(self) -> int:
        return self._lib.nns_ring_size(self._ring)

    def __del__(self):
        try:
            if self._ring:
                self._lib.nns_ring_free(self._ring)
                self._ring = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
