"""Native runtime bindings (libnnstpu.so via ctypes).

The reference's core is C (SURVEY.md §2.1); this package binds our native
equivalents — tensor-info utils, the buffer ring, and the custom-filter
C ABI loader — without pybind11 (not in the image): plain ctypes over a
stable C ABI (csrc/nns_custom.h).
"""
from .lib import NativeRing, load_native_lib, native_available

__all__ = ["load_native_lib", "native_available", "NativeRing"]
