"""Converter subplugin registry.

Kinds:
  * ``media``         — claims a media mimetype (auto-dispatch by caps name)
  * ``custom-code``   — in-process callable registered by name
                        (≙ NNS_custom_easy-style registration)
  * ``custom-script`` — a python file defining ``convert``/``get_out_config``
                        (≙ tensor_converter_python3.cc user scripts)
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig

_lock = threading.Lock()
_media: Dict[str, "ConverterPlugin"] = {}
_custom: Dict[str, "ConverterPlugin"] = {}


class ConverterPlugin:
    """get_out_config(caps) -> TensorsConfig; convert(buf) -> Buffer."""

    def get_out_config(self, incaps: Caps) -> TensorsConfig:
        raise NotImplementedError

    def convert(self, buf: Buffer) -> Optional[Buffer]:
        raise NotImplementedError


class _CallablePlugin(ConverterPlugin):
    def __init__(self, fn: Callable[[Buffer], Buffer],
                 out_config: "TensorsConfig | Callable[[Caps], TensorsConfig]"):
        self._fn = fn
        self._out = out_config

    def get_out_config(self, incaps: Caps) -> TensorsConfig:
        return self._out(incaps) if callable(self._out) else self._out

    def convert(self, buf: Buffer) -> Optional[Buffer]:
        return self._fn(buf)


def register_converter(name: str, plugin: "ConverterPlugin | Callable" = None,
                       media_type: Optional[str] = None,
                       out_config: Any = None):
    """Register a converter. With ``media_type``, it is auto-dispatched for
    that mimetype; otherwise it is a named custom-code converter."""
    def _store(p: ConverterPlugin):
        with _lock:
            if media_type:
                _media[media_type] = p
            _custom[name] = p
        return p

    if plugin is None:  # decorator form
        def deco(obj):
            p = obj() if isinstance(obj, type) else _CallablePlugin(obj, out_config)
            _store(p)
            return obj
        return deco
    p = plugin if isinstance(plugin, ConverterPlugin) else \
        _CallablePlugin(plugin, out_config)
    return _store(p)


def unregister_converter(name: str) -> None:
    with _lock:
        _custom.pop(name, None)


def _load_script(path: str) -> ConverterPlugin:
    ns: Dict[str, Any] = {}
    with open(path) as f:
        exec(compile(f.read(), path, "exec"), ns)  # noqa: S102 — user script
    if "convert" not in ns:
        raise ValueError(f"{path}: converter script must define convert()")
    return _CallablePlugin(ns["convert"], ns.get("get_out_config",
                                                 ns.get("out_config")))


def find_converter(kind: str, arg: str = "",
                   optional: bool = False) -> Optional[ConverterPlugin]:
    with _lock:
        if kind == "media":
            p = _media.get(arg)
        elif kind == "custom-code":
            p = _custom.get(arg)
        elif kind == "custom-script":
            p = _load_script(arg) if os.path.exists(arg) else None
        else:
            p = _custom.get(kind) or _media.get(kind)
    if p is None and not optional:
        raise ValueError(f"no converter for {kind}:{arg}")
    return p
