"""Converter subplugins: arbitrary media -> tensors.

≙ ext/nnstreamer/tensor_converter/* (flatbuf/flexbuf/protobuf/python3) and
the external-converter hook in gsttensor_converter.c (_NNS_MEDIA_ANY).
"""
from . import registry
from . import codecs  # noqa: F401  (register codec media converters)
from .registry import ConverterPlugin, find_converter, register_converter

__all__ = ["registry", "ConverterPlugin", "find_converter",
           "register_converter"]
