"""Codec converter subplugins: serialized byte streams -> tensors.

≙ ext/nnstreamer/tensor_converter/tensor_converter_flatbuf.cc,
-flexbuf.cc, -protobuf.cc. Registered as media converters so
tensor_converter auto-dispatches on the codec mimetypes. The payload is
self-describing (dims/dtypes ride in the message), so the negotiated
output is ``other/tensors,format=flexible``; a downstream
tensor_converter or the filter's push path pins static dims.
"""
from __future__ import annotations

from typing import Optional

from ..interop import tensor_codec as tc
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig, TensorsInfo
from ..tensors.types import TensorFormat
from .registry import ConverterPlugin, register_converter


class _CodecConverter(ConverterPlugin):
    UNPACK = None

    def get_out_config(self, incaps: Caps) -> TensorsConfig:
        rate = incaps.structures[0].fields.get("framerate")
        return TensorsConfig(TensorsInfo(), TensorFormat.FLEXIBLE,
                             getattr(rate, "numerator", 0),
                             getattr(rate, "denominator", 1))

    def convert(self, buf: Buffer) -> Optional[Buffer]:
        data = buf.chunks[0].host().tobytes()
        frame = type(self).UNPACK(data)
        out = Buffer([Chunk(a) for a in frame.arrays])
        out.copy_meta_from(buf)
        return out


class FlatbufConverter(_CodecConverter):
    UNPACK = staticmethod(tc.unpack_flatbuf)


class FlexbufConverter(_CodecConverter):
    UNPACK = staticmethod(tc.unpack_flexbuf)


class ProtobufConverter(_CodecConverter):
    UNPACK = staticmethod(tc.unpack_protobuf)


register_converter("flatbuf", FlatbufConverter(),
                   media_type="other/flatbuf-tensor")
register_converter("flexbuf", FlexbufConverter(),
                   media_type="other/flexbuf")
register_converter("protobuf", ProtobufConverter(),
                   media_type="other/protobuf-tensor")
