"""Pads: the typed connection points between elements.

The dataflow analog of GstPad. Src pads push buffers/events to their linked
peer sink pad; caps are negotiated by intersecting pad templates at link
time and fixed by the CAPS event at stream start (ref: GStreamer pad
negotiation as used by the reference's elements).
"""
from __future__ import annotations

import enum
import threading
from typing import TYPE_CHECKING, Optional

from ..tensors.caps import Caps

if TYPE_CHECKING:  # pragma: no cover
    from .element import Element


class PadDirection(enum.Enum):
    SRC = "src"
    SINK = "sink"


class FlowError(RuntimeError):
    """Downstream returned a fatal flow error."""


class Pad:
    def __init__(self, element: "Element", name: str, direction: PadDirection,
                 template: Optional[Caps] = None):
        self.element = element
        self.name = name
        self.direction = direction
        self.template = template if template is not None else Caps.ANY()
        self.peer: Optional["Pad"] = None
        self.caps: Optional[Caps] = None  # negotiated, fixed caps
        self._lock = threading.Lock()

    # -- linking ----------------------------------------------------------
    def link(self, sinkpad: "Pad") -> None:
        if self.direction != PadDirection.SRC or sinkpad.direction != PadDirection.SINK:
            raise ValueError(
                f"can only link src->sink, got {self.direction}->{sinkpad.direction}")
        if self.peer is not None or sinkpad.peer is not None:
            raise ValueError(f"pad already linked: {self} or {sinkpad}")
        if not self.template.can_intersect(sinkpad.template):
            raise ValueError(
                f"incompatible pad templates linking {self} -> {sinkpad}: "
                f"{self.template} vs {sinkpad.template}")
        self.peer = sinkpad
        sinkpad.peer = self

    def unlink(self) -> None:
        if self.peer is not None:
            self.peer.peer = None
            self.peer = None

    @property
    def is_linked(self) -> bool:
        return self.peer is not None

    # -- dataflow ---------------------------------------------------------
    def push(self, item) -> None:
        """Push a Buffer or Event to the linked peer (src pads only)."""
        assert self.direction == PadDirection.SRC, "push on sink pad"
        peer = self.peer
        if peer is None:
            return  # unlinked src pad: drop (like gst's not-linked on leaf)
        peer.element.chain(peer, item)

    def set_caps(self, caps: Caps) -> None:
        if not caps.is_fixed():
            raise ValueError(f"pad caps must be fixed, got {caps}")
        if not self.template.can_intersect(caps):
            raise ValueError(
                f"caps {caps} not accepted by template {self.template} on {self}")
        self.caps = caps

    def __repr__(self) -> str:
        ename = getattr(self.element, "name", "?")
        return f"<Pad {ename}.{self.name} {self.direction.value}>"
