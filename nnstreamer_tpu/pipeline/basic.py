"""Generic plumbing elements: queue, tee, capsfilter, identity, appsrc,
appsink, fakesink (the GStreamer core-element analogs the reference's
pipelines lean on, e.g. ``queue`` for thread boundaries and ``tee`` for
fan-out in composite pipelines, README.md multi-model examples)."""
from __future__ import annotations

import queue as _pyqueue
import threading
import time
from typing import Callable, List, Optional

from ..obs import context as _obs_ctx
from ..obs import spans as _obs_spans
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..utils.log import logger
from .element import (Element, SinkElement, SrcElement, TransferError,
                      TransformElement)
from .events import CapsEvent, EosEvent, Event
from .pad import FlowError, Pad, PadDirection
from .registry import register_element

_SENTINEL = object()


class _NativeQueueAdapter:
    """queue.Queue facade over the C++ MPMC ring (csrc/nns_ring.cc) —
    the native thread-boundary the reference gets from GStreamer's C
    queue. Waiting happens in native condition variables, off the GIL."""

    def __init__(self, capacity: int):
        from ..native.lib import NativeRing
        self._ring = NativeRing(capacity)

    def put(self, item) -> None:
        self._ring.push(item, -1)

    def put_nowait(self, item) -> None:
        if not self._ring.push(item, 0):
            raise _pyqueue.Full

    def get(self, timeout: Optional[float] = None):
        item = self._ring.pop(-1 if timeout is None else
                              max(0, int(timeout * 1000)))
        if item is None:
            raise _pyqueue.Empty
        return item

    def get_nowait(self):
        return self.get(timeout=0)

    def close(self) -> None:
        self._ring.close()

    def qsize(self) -> int:
        return len(self._ring)


@register_element("queue")
class Queue(Element):
    """Thread boundary with a bounded buffer queue.

    Backpressure: upstream ``chain`` blocks when the queue is full
    (matching gst queue defaults). GStreamer leaky semantics:
    ``leaky=upstream`` drops the incoming buffer when full;
    ``leaky=downstream`` evicts the oldest queued buffer to make room.

    ``backend=auto`` (default) uses the native C++ ring for the common
    non-leaky case when libnnstpu is built; ``python``/``native`` force
    one. Leaky modes always use the python queue (eviction needs its
    internals).
    """

    SINK_TEMPLATES = {"sink": None}
    SRC_TEMPLATES = {"src": None}
    PROPS = {"max-size-buffers": 16, "leaky": "none", "backend": "auto"}
    SPAN_POINTS = ("queue-wait",)

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._q = self._make_q()
        self._thread: Optional[threading.Thread] = None
        self._running = False

    def _make_q(self):
        cap = max(1, self.max_size_buffers)
        if self.backend in ("auto", "native") and self.leaky == "none":
            from ..native.lib import native_available, native_built
            # auto must never trigger an on-demand `make native` from a
            # plain pipeline parse — only use a lib already on disk;
            # explicit backend=native may build
            usable = (native_available() if self.backend == "native"
                      else native_built() and native_available())
            if usable:
                return _NativeQueueAdapter(cap)
            if self.backend == "native":
                raise RuntimeError(
                    f"{self.name}: backend=native but libnnstpu is not "
                    "built (run `make native`)")
        elif self.backend == "native":
            raise ValueError(
                f"{self.name}: leaky queues need backend=python")
        return _pyqueue.Queue(maxsize=cap)

    def set_property(self, key: str, value) -> None:
        super().set_property(key, value)
        if key.replace("_", "-") in ("max-size-buffers", "leaky", "backend"):
            # properties may be applied after __init__ (launch parser);
            # rebuild then — but never once the worker owns the queue.
            # During Element.__init__ (constructor kwargs) _q does not
            # exist yet: skip — Queue.__init__ builds it exactly once.
            if "_q" not in self.__dict__:
                return
            if getattr(self, "_running", False):
                raise RuntimeError(
                    f"{self.name}: cannot reconfigure a running queue")
            self._q = self._make_q()

    def start(self) -> None:
        super().start()
        self._running = True
        self._thread = threading.Thread(
            target=self._worker, name=f"queue:{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        super().stop()
        if isinstance(self._q, _NativeQueueAdapter):
            # the C++ ring has real shutdown: close() wakes BOTH blocked
            # producers (push returns 'closed') and the worker's pop.
            # The sentinel dance below can lose a race against a
            # producer re-filling the freed slot, wedging that producer
            # in the native cv forever (observed under CPU load).
            self._q.close()
        else:
            try:
                self._q.put_nowait(_SENTINEL)
            except _pyqueue.Full:
                try:
                    self._q.get_nowait()
                    self._q.put_nowait(_SENTINEL)
                except (_pyqueue.Empty, _pyqueue.Full):
                    pass
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)
            self._thread = None
        if isinstance(self._q, _NativeQueueAdapter):
            # a closed ring stays closed: rebuild so a restarted element
            # (rapid start/stop cycles) gets a live queue again
            self._q = self._make_q()

    def chain(self, pad: Pad, item) -> None:
        if isinstance(item, Event):
            self._q.put(item)  # events are serialized: never dropped
            return
        # the queue bypasses Element.chain (no do_chain), so the tracing
        # hook must fire here explicitly (stats['buffers'] is counted by
        # the worker on pop — counting here too would double it)
        tracer = getattr(self.pipeline, "tracer", None)
        if tracer is not None:
            tracer.record(self, item)
        if _obs_spans.ENABLED:
            # entry stamp: the worker's pop turns it into the
            # queue-wait span (+ queue attribution on the context)
            item.extras[_obs_ctx.QT_KEY] = time.time_ns()
        if self.leaky == "upstream":
            # GStreamer leaky=upstream: drop the incoming buffer when full
            try:
                self._q.put_nowait(item)
            except _pyqueue.Full:
                pass
        elif self.leaky == "downstream":
            # GStreamer leaky=downstream: evict the oldest queued BUFFER;
            # events keep their queue position (they are never dropped)
            while True:
                try:
                    self._q.put_nowait(item)
                    return
                except _pyqueue.Full:
                    dropped = False
                    with self._q.mutex:
                        for i, old in enumerate(self._q.queue):
                            if not isinstance(old, Event):
                                del self._q.queue[i]
                                dropped = True
                                # wake producers blocked in put(): mutex IS
                                # the not_full condition's lock
                                self._q.not_full.notify()
                                break
                    if not dropped:
                        # only events queued: block until the worker drains
                        self._q.put(item)
                        return
        else:
            self._q.put(item)  # blocking: backpressure

    def _worker(self) -> None:
        while self._running:
            try:
                item = self._q.get()
            except _pyqueue.Empty:
                break  # native ring closed and drained
            if item is _SENTINEL:
                break
            try:
                if isinstance(item, Event):
                    if isinstance(item, CapsEvent):
                        self.sinkpad.set_caps(item.caps)
                        self.set_src_caps(item.caps)
                    else:
                        self.forward_event(item)
                else:
                    self.stats.add(buffers=1, bytes=item.nbytes)
                    if _obs_spans.ENABLED:
                        qt = item.extras.pop(_obs_ctx.QT_KEY, None)
                        if qt is not None:
                            ctx = item.extras.get(_obs_ctx.CTX_KEY)
                            if ctx is not None:
                                wait = max(0, time.time_ns() - qt)
                                _obs_spans.record_span(self.name, "queue",
                                                       qt, wait, ctx)
                                ctx.q_ns += wait
                    self.srcpad.push(item)
            except FlowError:
                break
            except Exception as exc:  # noqa: BLE001
                logger.exception("%s: error in queue worker", self.name)
                self.post_error(exc)
                break


@register_element("tee")
class Tee(Element):
    """1-to-N fan-out. Buffers are shared, not copied: chunks are
    immutable by convention (device arrays are immutable anyway)."""

    SINK_TEMPLATES = {"sink": None}
    SRC_TEMPLATES = {"src_%u": None}

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        for p in self.src_pads.values():
            if p.is_linked:
                p.push(buf)

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self.set_src_caps(caps)


@register_element("capsfilter")
class CapsFilter(TransformElement):
    """Pass-through that restricts negotiation to its ``caps`` property."""

    PROPS = {"caps": ""}

    def transform(self, buf: Buffer) -> Buffer:
        return buf

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        if not self.caps:
            return incaps
        want = Caps(self.caps) if isinstance(self.caps, str) else self.caps
        out = incaps.intersect(want)
        if out.is_empty():
            raise ValueError(
                f"{self.name}: caps {incaps} do not satisfy filter {want}")
        return out.fixate() if not out.is_fixed() else out

    def static_transfer(self, in_caps):
        """Input ∩ ``caps`` property; a fixed caps property alone pins
        an otherwise-unknown upstream."""
        if in_caps.get("sink") is None and self.caps:
            want = Caps(self.caps) if isinstance(self.caps, str) else self.caps
            if want.is_fixed():
                return {"src": want}
            return {"src": None}
        return super().static_transfer(in_caps)


@register_element("identity")
class Identity(TransformElement):
    PROPS = {"silent": True}

    def transform(self, buf: Buffer) -> Buffer:
        if not self.silent:
            logger.info("%s: buffer pts=%s chunks=%d", self.name, buf.pts, len(buf))
        return buf


@register_element("appsrc")
class AppSrc(SrcElement):
    """Application-driven source: the app thread calls ``push_buffer`` /
    ``end_stream``; the src loop relays into the pipeline."""

    PROPS = {"caps": "", "max-buffers": 64}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._q: _pyqueue.Queue = _pyqueue.Queue(maxsize=max(1, self.max_buffers))

    def push_buffer(self, buf: Buffer) -> None:
        self._q.put(buf)

    def end_stream(self) -> None:
        self._q.put(_SENTINEL)

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(self.caps) if self.caps else None

    def create(self) -> Optional[Buffer]:
        while not self._stop_evt.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except _pyqueue.Empty:
                continue
            return None if item is _SENTINEL else item
        return None


@register_element("appsink")
class AppSink(SinkElement):
    """Collecting sink with an optional new-data callback
    (≙ tensor_sink's ``new-data`` signal, ref: gsttensor_sink.c)."""

    PROPS = {"max-buffers": 0, "emit-signals": True}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.buffers: List[Buffer] = []
        self.callback: Optional[Callable[[Buffer], None]] = None
        self._lock = threading.Lock()

    def connect(self, callback: Callable[[Buffer], None]) -> None:
        self.callback = callback

    def render(self, buf: Buffer) -> None:
        with self._lock:
            self.buffers.append(buf)
            if self.max_buffers > 0 and len(self.buffers) > self.max_buffers:
                self.buffers.pop(0)
        if self.callback is not None:
            self.callback(buf)

    def pop_all(self) -> List[Buffer]:
        with self._lock:
            out, self.buffers = self.buffers, []
            return out


@register_element("tensortestsrc")
class TensorTestSrc(SrcElement):
    """Synthetic tensor source (≙ videotestsrc feeding tensor_converter in
    reference test pipelines). Generates frames matching its ``caps``
    property with a chosen fill pattern; PTS synthesized from framerate."""

    # device=true pre-stages a pool of frames in HBM and cycles them, so
    # the stream is device-resident from the source on (MLPerf-offline
    # style): downstream device elements see zero H2D cost, isolating
    # the runtime's own per-buffer overhead from the host link.
    # unique=true additionally adds the frame counter to each pooled
    # frame ON DEVICE (one tiny fused op, no host bytes), so every
    # emitted frame is distinct — a remote transport that caches repeat
    # executions by (executable, args) cannot serve pool repeats from
    # cache and fake downstream throughput. Off by default: it perturbs
    # frame CONTENT, which belongs to benchmark configs, not to
    # pipelines that verify pattern semantics.
    PROPS = {"caps": "", "pattern": "counter", "seed": 0, "is-live": False,
             "device": False, "pool-size": 4, "unique": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._config = None
        self._count = 0
        self._rng = None
        self._pool = None
        self._uniq = None

    def static_src_caps(self) -> Optional[Caps]:
        """Fixated ``caps`` property (required for this source)."""
        if not self.caps:
            raise TransferError(f"{self.name}: 'caps' property is required")
        return super().static_src_caps()

    def negotiate_src_caps(self) -> Optional[Caps]:
        if not self.caps:
            raise ValueError(f"{self.name}: 'caps' property is required")
        caps = Caps(self.caps)
        if not caps.is_fixed():
            caps = caps.fixate()
        self._config = caps.to_config()
        return caps

    def _make_frame(self, count: int):
        import numpy as np
        arrays = []
        for info in self._config.info:
            dt = info.type.np_dtype
            if self.pattern == "zeros":
                arr = np.zeros(info.shape, dtype=dt)
            elif self.pattern == "ones":
                arr = np.ones(info.shape, dtype=dt)
            elif self.pattern == "random":
                if np.issubdtype(np.dtype(dt), np.integer):
                    ii = np.iinfo(dt)
                    arr = self._rng.integers(ii.min, ii.max, info.shape,
                                             dtype=dt, endpoint=True)
                else:
                    arr = self._rng.random(info.shape).astype(dt)
            else:  # counter
                arr = np.full(info.shape, count).astype(dt)
            arrays.append(arr)
        return arrays

    def create(self) -> Optional[Buffer]:
        import numpy as np
        if self._rng is None:
            self._rng = np.random.default_rng(self.seed)
        if self.device:
            if self._pool is None:
                import jax
                n = max(1, int(self.pool_size))
                self._pool = [
                    [Chunk(jax.device_put(a)) for a in self._make_frame(i)]
                    for i in range(n)]
                if self.unique:
                    self._uniq = jax.jit(lambda a, s: a + s)
            chunks = self._pool[self._count % len(self._pool)]
            if self._uniq is not None:
                chunks = [Chunk(self._uniq(
                    c.raw, np.asarray(self._count % 199 + 1).astype(c.dtype)))
                    for c in chunks]
        else:
            chunks = [Chunk(a) for a in self._make_frame(self._count)]
        cfg = self._config
        dur = cfg.frame_duration_ns()
        pts = self._count * dur if dur else self._count
        self._count += 1
        if self.is_live and dur:
            import time as _t
            _t.sleep(dur / 1e9)
        return Buffer(chunks, pts=pts, duration=dur)


@register_element("fakesink")
class FakeSink(SinkElement):
    PROPS = {"dump": False}

    def render(self, buf: Buffer) -> None:
        if self.dump:
            logger.info("%s: pts=%s %r", self.name, buf.pts, buf)
