"""Dataflow pipeline runtime (L0/L3 skeleton)."""
from . import basic  # noqa: F401  (registers core elements)
from .element import Element, SinkElement, SrcElement, TransformElement
from .events import (CapsEvent, CustomEvent, EosEvent, Event, FlushEvent,
                     SegmentEvent, StreamStart)
from .pad import FlowError, Pad, PadDirection
from .parser import parse_launch
from .pipeline import Bus, Message, Pipeline
from .registry import element_names, make_element, register_element

__all__ = [
    "Element", "SrcElement", "SinkElement", "TransformElement", "Pad",
    "PadDirection", "FlowError", "Pipeline", "Bus", "Message", "parse_launch",
    "register_element", "make_element", "element_names", "Event", "CapsEvent",
    "EosEvent", "StreamStart", "SegmentEvent", "FlushEvent", "CustomEvent",
]
