"""Element base classes: the dataflow node model.

The analog of GstElement/GstBaseTransform/GstBaseSrc/GstBaseSink, without
GObject: elements declare pad templates and string-typed properties, chain
buffers synchronously within a thread segment, and negotiate caps via
in-band CAPS events. Thread boundaries are explicit ``queue`` elements and
source loops, mirroring GStreamer's scheduling model (SURVEY.md §1: each
queue/src boundary runs its own streaming thread).

Per-element proctime statistics are built in (≙ GstShark proctime tracer,
SURVEY.md §5 tracing).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Union

from ..obs import context as _obs_ctx
from ..obs import spans as _obs_spans
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.atomic import Counters
from ..utils.log import logger
from .events import (CapsEvent, EosEvent, Event, FlushEvent, QosEvent,
                     SegmentEvent, StreamStart)
from .pad import FlowError, Pad, PadDirection


class TransferError(ValueError):
    """A declared caps transfer provably cannot succeed (static analog of
    a runtime negotiation failure). ``pad`` names the sink pad where the
    contradiction was detected, when known."""

    def __init__(self, message: str, pad: Optional[str] = None):
        super().__init__(message)
        self.pad = pad


def _coerce(value: str, default: Any) -> Any:
    """Coerce a launch-string property value to the default's type."""
    if not isinstance(value, str):
        return value
    if isinstance(default, bool):
        return value.strip().lower() in ("true", "1", "yes", "on")
    if isinstance(default, int) and not isinstance(default, bool):
        return int(value)
    if isinstance(default, float):
        return float(value)
    return value


class Element:
    """Base dataflow element.

    Subclasses declare:
      * ``SINK_TEMPLATES`` / ``SRC_TEMPLATES``: dict of pad-name -> caps
        string (or None for ANY). Names ending in ``_%u`` are request-pad
        templates (``sink_%u`` like the reference's mux).
      * ``PROPS``: dict of property-name -> default value (types inferred).
    """

    SINK_TEMPLATES: Dict[str, Optional[str]] = {}
    SRC_TEMPLATES: Dict[str, Optional[str]] = {}
    # every element accepts on-error (fault/policy.py grammar):
    # fail | skip | retry[(n[,backoff_s[,jitter]])] |
    # restart[(budget[,window_s])]. Default preserves the historical
    # behavior: any chain exception aborts the pipeline.
    PROPS: Dict[str, Any] = {"on-error": "fail"}
    # elements opting into on_error=restart declare that stop()/start()
    # rebuilds them losslessly (pipelint errors on restart otherwise)
    RESTART_SAFE = False
    # per-element observability span points (Documentation/observability
    # .md; gen_element_docs.py emits these per element): where this
    # element records frame spans into the flight recorder
    SPAN_POINTS = ("chain",)
    # elements that mint FRESH output buffers without copying the input
    # buffer's extras declare it: the trace context then survives only
    # through same-thread inheritance, and pipelint's trace-export rule
    # warns when such an element sits between a trace-exporting source
    # and a wire hop (analysis/rules.py TraceExportRule)
    STRIPS_META = False

    _anon_counter = [0]

    def __init__(self, name: Optional[str] = None, **props):
        if name is None:
            Element._anon_counter[0] += 1
            name = f"{type(self).__name__.lower()}{Element._anon_counter[0]}"
        self.name = name
        self.pipeline = None  # set by Pipeline.add
        # per-element-kind debug category (≙ GST_DEBUG_CATEGORY per
        # element; level via NNS_TPU_DEBUG="tensor_filter:DEBUG,...")
        from ..utils.log import category
        self.log = category(getattr(type(self), "ELEMENT_NAME",
                                    type(self).__name__.lower()))
        self.sink_pads: Dict[str, Pad] = {}
        self.src_pads: Dict[str, Pad] = {}
        self._eos_seen: set = set()
        self._started = False
        # atomic counter map: chain threads, the fault supervisor, and
        # network reader threads all mutate these while Pipeline.stats()
        # and trace.report() read them from the user thread
        self.stats = Counters({"buffers": 0, "bytes": 0, "proctime_ns": 0,
                               "events": 0,
                               # fault-policy accounting (fault/policy.py):
                               # buffers skipped/shed, retried, and how
                               # often on-error=restart bounced the element
                               "dropped": 0, "retries": 0, "restarts": 0})
        # merged property table from the full class hierarchy
        self._prop_defaults: Dict[str, Any] = {}
        for klass in reversed(type(self).__mro__):
            self._prop_defaults.update(getattr(klass, "PROPS", {}))
        for k, v in self._prop_defaults.items():
            setattr(self, k.replace("-", "_"), v)
        for k, v in props.items():
            self.set_property(k.replace("_", "-") if "-" not in k else k, v)
        for pname, caps_str in self.SINK_TEMPLATES.items():
            if not pname.endswith("%u"):
                self._make_pad(pname, PadDirection.SINK, caps_str)
        for pname, caps_str in self.SRC_TEMPLATES.items():
            if not pname.endswith("%u"):
                self._make_pad(pname, PadDirection.SRC, caps_str)

    # -- pads -------------------------------------------------------------
    def _make_pad(self, name: str, direction: PadDirection,
                  caps_str: Optional[str]) -> Pad:
        tmpl = Caps.ANY() if caps_str is None else Caps(caps_str)
        pad = Pad(self, name, direction, tmpl)
        (self.sink_pads if direction == PadDirection.SINK else self.src_pads)[name] = pad
        return pad

    def request_pad(self, direction: PadDirection) -> Pad:
        """Create a pad from a ``_%u`` request template (mux/demux style)."""
        templates = (self.SINK_TEMPLATES if direction == PadDirection.SINK
                     else self.SRC_TEMPLATES)
        pads = self.sink_pads if direction == PadDirection.SINK else self.src_pads
        for tname, caps_str in templates.items():
            if tname.endswith("%u"):
                base = tname[:-2]
                idx = 0
                while f"{base}{idx}" in pads:
                    idx += 1
                return self._make_pad(f"{base}{idx}", direction, caps_str)
        raise ValueError(f"{self.name}: no request-pad template for {direction}")

    @property
    def sinkpad(self) -> Pad:
        return next(iter(self.sink_pads.values()))

    @property
    def srcpad(self) -> Pad:
        return next(iter(self.src_pads.values()))

    def get_static_or_request_pad(self, name: str, direction: PadDirection) -> Pad:
        pads = self.sink_pads if direction == PadDirection.SINK else self.src_pads
        if name in pads:
            return pads[name]
        pad = self.request_pad(direction)
        if name != pad.name:
            pads[name] = pads.pop(pad.name)
            pad.name = name
        return pad

    # -- properties -------------------------------------------------------
    def set_property(self, key: str, value: Any) -> None:
        attr = key.replace("-", "_")
        dashed = key.replace("_", "-")
        if key in self._prop_defaults:
            setattr(self, attr, _coerce(value, self._prop_defaults[key]))
        elif attr in self._prop_defaults:
            setattr(self, attr, _coerce(value, self._prop_defaults[attr]))
        elif dashed in self._prop_defaults:
            # launch strings may spell a dashed property with
            # underscores (on_error=skip for on-error)
            setattr(self, attr, _coerce(value, self._prop_defaults[dashed]))
        else:
            raise ValueError(f"{type(self).__name__} has no property {key!r}")

    def get_property(self, key: str) -> Any:
        return getattr(self, key.replace("-", "_"))

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        """Transition to running; override for resource setup."""
        self._started = True

    def stop(self) -> None:
        self._started = False

    # -- dataflow ---------------------------------------------------------
    def chain(self, pad: Pad, item: Union[Buffer, Event]) -> None:
        """Entry point for data arriving on a sink pad."""
        if isinstance(item, Event):
            self.stats.inc("events")
            self.handle_event(pad, item)
            return
        tracer = getattr(self.pipeline, "tracer", None)
        if tracer is not None:
            tracer.record(self, item)
        t_wall = time.time_ns() if _obs_spans.ENABLED else 0
        t0 = time.perf_counter_ns()
        try:
            self.do_chain(pad, item)
        except FlowError:
            raise
        except Exception as exc:  # noqa: BLE001 -- apply the element's on-error policy
            # fail (default) posts the error and raises FlowError like
            # GST_ELEMENT_ERROR always did; skip/retry/restart may
            # consume or recover the buffer (fault/policy.py)
            from ..fault.policy import handle_chain_error
            if not handle_chain_error(self, pad, item, exc):
                return  # buffer consumed by the policy (skipped)
        dt = time.perf_counter_ns() - t0
        # one lock round-trip for the whole per-buffer bump
        self.stats.add(buffers=1, bytes=item.nbytes, proctime_ns=dt)
        if _obs_spans.ENABLED:
            # per-hop frame span into the per-thread ring (obs/spans.py)
            _obs_spans.chain_span(self, item, t_wall, dt)

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        raise NotImplementedError

    # -- events -----------------------------------------------------------
    def handle_event(self, pad: Pad, event: Event) -> None:
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
        elif isinstance(event, EosEvent):
            self._eos_seen.add(pad.name)
            linked = [p for p in self.sink_pads.values() if p.is_linked]
            if all(p.name in self._eos_seen for p in linked):
                self.on_eos()
                self.forward_event(event)
        else:
            self.forward_event(event)

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        """Default single-in/single-out negotiation: compute src caps and
        forward. Multi-pad elements override."""
        out = self.transform_caps(caps)
        if out is None:
            raise ValueError(f"{self.name}: cannot negotiate caps {caps}")
        self.set_src_caps(out)

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        """in caps -> out caps; identity by default (passthrough)."""
        return incaps

    # -- static analysis (pipelint) ---------------------------------------
    def static_src_caps(self) -> Optional[Caps]:
        """Declared output caps of a source element, computed WITHOUT
        starting it. Default: the fixated ``caps`` property when the
        element declares one; None (unknown) otherwise."""
        caps_str = getattr(self, "caps", None)
        if isinstance(caps_str, str) and caps_str:
            try:
                return Caps(caps_str).fixate()
            except ValueError as exc:
                raise TransferError(
                    f"{self.name}: bad caps property {caps_str!r}: {exc}")
        return None

    def static_transfer(
            self, in_caps: Dict[str, Optional[Caps]],
    ) -> Dict[str, Optional[Caps]]:
        """Declared caps transfer: map per-sink-pad input caps to per-src-
        pad output caps without executing the element. ``None`` marks an
        unknown (gradual typing) — rules only fire on known caps. Raise
        :class:`TransferError` for a provable contradiction.

        Default declaration: sources answer :meth:`static_src_caps`,
        single-sink elements pass their input through to every src pad,
        and multi-sink elements are unknown (override to say more)."""
        if not self.sink_pads:
            caps = self.static_src_caps()
            return {p: caps for p in self.src_pads}
        if len(in_caps) == 1:
            caps = next(iter(in_caps.values()))
            return {p: caps for p in self.src_pads}
        return {p: None for p in self.src_pads}

    # -- device placement (fusion compiler) -------------------------------
    # one-line capability note for docs/pipelint: None means the element
    # never provides a device function; a string describes when it does
    # (see Documentation/fusion.md and fusion/planner.py)
    DEVICE_FUSIBLE: Optional[str] = None

    def device_veto(self) -> Optional[str]:
        """Static reason this element can NOT provide a device function,
        or None when :meth:`device_fn` is expected to return a program.
        Declared next to :meth:`static_transfer` and held to the same
        discipline: pipelint calls it, so it must never open models,
        sockets, or devices. The planner still calls :meth:`device_fn`
        afterwards (which may decline with None for config-specific
        reasons)."""
        if type(self).device_fn is Element.device_fn:
            return "no device function"
        return None

    def device_fn(self, ctx=None):
        """Pure, traceable device-side body of this element, or None.

        Returns a callable ``fn(arrays: List[Array]) -> List[Array]``
        mapping the chunks of one input buffer to the chunks of one
        output buffer, composed of jax-traceable ops only (no Python
        side effects, no host round trips) — the fusion planner
        composes consecutive members' fns into one ``jax.jit`` program
        (fusion/segment.py). ``ctx`` is a :class:`fusion.FusionCtx`
        carrying the statically planned input caps/config. Unlike
        :meth:`device_veto` this runs at plan time (after validation,
        before start) and MAY open the element's model/subplugin; return
        None to decline, and the element keeps its per-buffer chain
        path."""
        return None

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    # one-line capability note for docs/pipelint: None means the element
    # holds no state worth snapshotting; a string describes what
    # snapshot_state() persists (see Documentation/robustness.md —
    # "surviving preemption" — and checkpoint/store.py)
    CHECKPOINTABLE: Optional[str] = None

    def snapshot_state(self, snap_dir: str) -> Optional[Dict]:
        """Serialize this element's live state for a crash-consistent
        snapshot. ``snap_dir`` is a per-element scratch directory inside
        the snapshot-in-progress for bulk artifacts (the trainer's orbax
        params tree); the returned dict is pickled as the element's
        blob, and both are integrity-hashed into the snapshot manifest.
        Return None for "no state right now" (no blob written). Base:
        stateless, never called (Pipeline.snapshot only collects from
        overriders)."""
        return None

    def restore_state(self, state: Dict, snap_dir: str) -> None:
        """Rebuild state captured by :meth:`snapshot_state`. Called by
        ``Pipeline.restore`` BEFORE ``start()`` — elements whose backing
        resources come up in start() stash the state and apply it
        there."""

    def preempt(self) -> None:
        """Preemption quiesce hook (``Pipeline.preempt``): cheap and
        non-blocking — stop admitting new work and nudge in-flight work
        toward completion, but never wait. Runs even on the degraded
        (no-drain) path, so side effects that must reach peers (a serve
        source's DRAIN notify to its router) belong here. Default:
        delegate to :meth:`drain`. Elements whose drain() *finishes*
        work rather than stopping it (the trainer runs epochs to
        completion) override to pause instead."""
        self.drain()

    def preempt_inflight(self) -> int:
        """Frames this element has admitted but not yet settled, counted
        at snapshot time when the grace deadline forced the no-drain
        path. Whatever is reported here is *declared* abandoned in the
        preempt report and snapshot manifest — never silently lost."""
        return 0

    def set_src_caps(self, caps: Caps, pad: Optional[Pad] = None) -> None:
        pads = [pad] if pad is not None else list(self.src_pads.values())
        for p in pads:
            p.set_caps(caps)
            p.push(CapsEvent(caps))

    def on_eos(self) -> None:
        """Hook before EOS is forwarded (flush pending data here)."""

    def forward_event(self, event: Event) -> None:
        for p in self.src_pads.values():
            if p.is_linked:
                p.push(event)

    # -- upstream events ---------------------------------------------------
    def send_upstream_event(self, event: Event) -> None:
        """Send an out-of-band event upstream (≙ gst_pad_push_event on a
        sink pad — the QoS path). Travels sink-pad → upstream element's
        ``handle_upstream_event`` directly, bypassing queues, like
        GStreamer's non-serialized upstream events."""
        for p in self.sink_pads.values():
            if p.is_linked:
                p.peer.element.handle_upstream_event(p.peer, event)

    def handle_upstream_event(self, pad: Pad, event: Event) -> None:
        """Default: keep propagating toward the source."""
        self.send_upstream_event(event)

    # -- push helpers -----------------------------------------------------
    def push(self, buf: Buffer, pad: Optional[Pad] = None) -> None:
        (pad or self.srcpad).push(buf)

    def post_error(self, exc: Exception) -> None:
        if self.pipeline is not None:
            self.pipeline.post_message("error", element=self.name, error=exc)

    def post_message(self, kind: str, **data) -> None:
        if self.pipeline is not None:
            self.pipeline.post_message(kind, element=self.name, **data)

    def drain(self) -> None:
        """Graceful-teardown hook (``Pipeline.drain``): stop admitting
        new work but finish what is already in flight — after every
        element drains, EOS reaches the sinks and the pipeline closes
        with nothing half-done. Base: nothing to do (pure per-buffer
        elements hold no work between chain calls)."""

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class TransformElement(Element):
    """1-in/1-out element (≙ GstBaseTransform)."""

    SINK_TEMPLATES = {"sink": None}
    SRC_TEMPLATES = {"src": None}
    # pure per-buffer transforms rebuild losslessly from stop()/start();
    # transforms that accumulate cross-buffer state (aggregator,
    # trainer, rate) opt back out
    RESTART_SAFE = True

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        out = self.transform(buf)
        if out is not None:
            self.push(out)

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        raise NotImplementedError

    def static_transfer(self, in_caps):
        """Pure ``transform_caps`` on the declared input caps."""
        incaps = in_caps.get("sink")
        if incaps is None:
            return {p: None for p in self.src_pads}
        out = self.transform_caps(incaps)
        if out is None:
            raise TransferError(
                f"{self.name}: cannot negotiate caps {incaps}", pad="sink")
        return {p: out for p in self.src_pads}


class _StreamRestart(Exception):
    """Control flow: a supervised create() failure was decided RESTART
    inside _stream; _loop replays the preamble without re-handling."""

    def __init__(self, cause: Exception):
        super().__init__(str(cause))
        self.cause = cause


class _StreamEscalate(Exception):
    """Control flow: a supervised create() failure exhausted its policy
    inside _stream; _loop posts the pipeline error without re-handling."""

    def __init__(self, cause: Exception):
        super().__init__(str(cause))
        self.cause = cause


class SrcElement(Element):
    """Source with its own streaming thread (≙ GstBaseSrc).

    Subclasses implement ``negotiate_src_caps()`` (fixed caps for the
    stream) and ``create()`` returning a Buffer or None for EOS. The
    thread runs supervised: see :meth:`_loop` and fault/supervisor.py.
    """

    SRC_TEMPLATES = {"src": None}
    # trace-export declares INTENT that this source's frame traces
    # survive to the sinks and across wire hops (pipelint's
    # TraceExportRule checks nothing downstream strips the context);
    # recording itself is always on (obs/, NNS_TPU_OBS=0 to disable)
    PROPS = {"num-buffers": -1, "trace-export": False}
    # restart for a source is a loop-level stream replay (on_restart
    # hook + preamble), which every source supports by construction
    RESTART_SAFE = True
    SPAN_POINTS = ("source-root", "chain")

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._drain_evt = threading.Event()
        self._pushed = 0

    def negotiate_src_caps(self) -> Optional[Caps]:
        return None

    def create(self) -> Optional[Buffer]:
        raise NotImplementedError

    def start(self) -> None:
        super().start()
        self._stop_evt.clear()
        self._drain_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"src:{self.name}", daemon=True)
        self._thread.start()

    def drain(self) -> None:
        """Ask the streaming loop to end the stream gracefully: no new
        admissions, flush what is queued (:meth:`drain_flushed`), then
        EOS. Subclasses that block in create() should also wake it."""
        self._drain_evt.set()

    def drain_flushed(self) -> bool:
        """True once everything this source already admitted has been
        pushed — the drain barrier for sources that queue internally
        (serversrc/servesrc/edgesrc override)."""
        return True

    def stop(self) -> None:
        self._stop_evt.set()
        super().stop()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)
            self._thread = None

    def on_restart(self) -> None:
        """Hook for supervised stream restarts (on-error=restart):
        re-acquire whatever resource the stream reads from (re-open a
        socket, re-subscribe). The preamble — StreamStart, caps,
        segment — is replayed by the loop itself."""

    def _loop(self) -> None:
        """Supervised streaming loop: failures escaping the stream body
        go through a fault.Supervisor applying the element's on-error
        policy (backoff + jitter, restart budget) before the historical
        escalate-to-pipeline-error path (fault/supervisor.py)."""
        from ..fault.supervisor import CONTINUE, RESTART, Supervisor
        try:
            sup = Supervisor(self)
        except Exception as exc:  # noqa: BLE001 — unparseable on-error spec
            logger.exception("%s: bad on-error policy", self.name)
            self.post_error(exc)
            return
        while not self._stop_evt.is_set():
            try:
                self._stream(sup)
                return
            except FlowError:
                return  # error already posted by the failing element
            except _StreamRestart:
                try:
                    self.on_restart()
                    continue  # replay preamble: caps re-negotiated
                except Exception as exc:  # noqa: BLE001
                    logger.exception("%s: restart hook failed", self.name)
                    self.post_error(exc)
                    return
            except Exception as exc:  # noqa: BLE001
                if isinstance(exc, _StreamEscalate):
                    exc = exc.cause
                else:
                    decision = sup.handle(exc, where="src-loop")
                    if decision == RESTART:
                        try:
                            self.on_restart()
                            continue
                        except Exception as exc2:  # noqa: BLE001
                            exc = exc2
                    elif decision == CONTINUE:
                        continue
                logger.exception("%s: error in src loop", self.name)
                self.post_error(exc)
                return

    def _stream(self, sup=None) -> None:
        """One full streaming pass: preamble, create() loop, EOS."""
        self.srcpad.push(StreamStart(stream_id=self.name))
        caps = self.negotiate_src_caps()
        if caps is not None:
            self.set_src_caps(caps)
        self.srcpad.push(SegmentEvent())
        while not self._stop_evt.is_set():
            if 0 <= self.num_buffers <= self._pushed:
                break
            if self._drain_evt.is_set() and self.drain_flushed():
                break  # drained: everything admitted has been pushed
            try:
                buf = self.create()
            except FlowError:
                raise
            except Exception as exc:  # noqa: BLE001 — per-frame policy site
                if sup is None:
                    raise
                from ..fault.supervisor import CONTINUE, RESTART
                decision = sup.handle(exc, where="create")
                if decision == CONTINUE:
                    continue  # frame skipped or retry backoff elapsed
                # the decision (budget slot, backoff, bus warning) is
                # already made — _loop must honor it, not re-handle
                if decision == RESTART:
                    raise _StreamRestart(exc) from exc
                raise _StreamEscalate(exc) from exc
            if sup is not None:
                sup.ok()
            if buf is None:
                break
            tracer = getattr(self.pipeline, "tracer", None)
            if tracer is not None:
                tracer.stamp(buf)
            if _obs_spans.ENABLED and _obs_ctx.ctx_of(buf) is None:
                # root of this frame's span tree (a source that already
                # attached a context — serve batch adoption — keeps it)
                _obs_spans.record_root(self.name, _obs_ctx.stamp(buf))
            self.srcpad.push(buf)
            self._pushed += 1
        self.srcpad.push(EosEvent())


class SinkElement(Element):
    """Terminal element (≙ GstBaseSink); notifies the pipeline on EOS.

    ``qos=true`` measures each render against the stream's frame
    duration and feeds QoS events upstream when the sink falls behind
    (≙ GstBaseSink's "qos" property + gst_base_sink_send_qos). This is
    the weather-adaptive loop on a tunnel-attached chip: a degrading
    link inflates the host materialization inside render, the upstream
    tensor_filter's throttle engages (tensor_filter.c:532-584 analog),
    and queues drain by DROPPING at the filter — no invoke, no fetch
    ticket, no ballooning backlog. Requires timestamped streams (a
    framerate, hence buf.duration); untimed streams already self-limit
    through bounded-queue backpressure."""

    SINK_TEMPLATES = {"sink": None}
    PROPS = {"qos": False}

    def __init__(self, name: Optional[str] = None, **props):
        super().__init__(name, **props)
        self._qos_avg_ns = 0.0
        self._qos_throttling = False
        self._qos_sent_ns = 0.0

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        if not self.qos or not buf.duration:
            self.render(buf)
            return
        t0 = time.perf_counter_ns()
        self.render(buf)
        dt = time.perf_counter_ns() - t0
        # EWMA over ~8 frames: tolerant of one-frame weather spikes,
        # fast enough to catch a drifting link
        self._qos_avg_ns += (dt - self._qos_avg_ns) * 0.125
        proportion = self._qos_avg_ns / buf.duration
        if proportion > 1.0:
            # one event per throttle EPISODE (the flowctl.py:216
            # convention), re-sent only when the sustainable period has
            # drifted >25% — not one per slow frame
            drift = abs(self._qos_avg_ns - self._qos_sent_ns) \
                > 0.25 * self._qos_sent_ns
            if not self._qos_throttling or drift:
                self._qos_throttling = True
                self._qos_sent_ns = self._qos_avg_ns
                self.send_upstream_event(QosEvent(
                    proportion=proportion,
                    period_ns=int(self._qos_avg_ns), timestamp=buf.pts))
        elif self._qos_throttling and proportion < 0.8:
            # weather recovered (hysteresis): release the throttle
            self._qos_throttling = False
            self._qos_sent_ns = 0.0
            self.send_upstream_event(QosEvent(
                proportion=1.0, period_ns=0, timestamp=buf.pts))

    def render(self, buf: Buffer) -> None:
        raise NotImplementedError

    def on_eos(self) -> None:
        if self.pipeline is not None:
            self.pipeline._sink_eos(self)
