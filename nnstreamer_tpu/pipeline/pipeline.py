"""Pipeline container, bus, and state management.

The analog of GstPipeline + GstBus: owns elements, drives start/stop,
aggregates sink EOS into a pipeline-level EOS message, and carries error/
latency messages out-of-band (ref: the reference relies on GStreamer's
pipeline/bus; SURVEY.md §1 L0).
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.log import logger
from .element import Element, SinkElement, SrcElement
from .pad import PadDirection


@dataclass
class Message:
    kind: str                    # "eos" | "error" | "latency" | element-custom
    data: Dict[str, Any] = field(default_factory=dict)


class Bus:
    def __init__(self):
        self._q: _queue.Queue = _queue.Queue()

    def post(self, msg: Message) -> None:
        self._q.put(msg)

    def pop(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            return self._q.get(timeout=timeout)
        except _queue.Empty:
            return None

    def drain(self) -> List[Message]:
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except _queue.Empty:
                return out


class Pipeline:
    def __init__(self, name: str = "pipeline0"):
        self.name = name
        self.elements: Dict[str, Element] = {}
        self.bus = Bus()
        self._sinks_eos: set = set()
        self._eos_evt = threading.Event()
        self._error: Optional[Exception] = None
        self._lock = threading.Lock()
        self.running = False
        self.tracer = None  # set by enable_tracing()
        # pre-PLAYING static validation gate (pipelint); set False to
        # launch a pipeline the analyzer rejects (escape hatch)
        self.validate_on_start = True
        # fusion compiler (fusion/): compile maximal device-capable runs
        # into FusedSegments at start. ``fuse=false`` as a pipeline-level
        # launch prop (or this attr) keeps the per-element chain path —
        # the parity oracle and the escape hatch.
        self.fuse = True
        self._fusion_plan = None

    def enable_tracing(self):
        """Attach a Tracer (≙ GstShark proctime/interlatency/framerate
        tracers, SURVEY.md §5); returns it for report()."""
        from ..utils.trace import Tracer
        self.tracer = Tracer()
        return self.tracer

    # -- graph construction ----------------------------------------------
    def add(self, *elements: Element) -> "Pipeline":
        for e in elements:
            if e.name in self.elements:
                raise ValueError(f"duplicate element name {e.name!r}")
            self.elements[e.name] = e
            e.pipeline = self
        return self

    def __getitem__(self, name: str) -> Element:
        return self.elements[name]

    def link(self, *elements: Element) -> "Pipeline":
        """Link a chain of elements src->sink, requesting pads as needed."""
        for up, down in zip(elements, elements[1:]):
            srcpad = next(
                (p for p in up.src_pads.values() if not p.is_linked), None)
            if srcpad is None:
                srcpad = up.request_pad(PadDirection.SRC)
            sinkpad = next(
                (p for p in down.sink_pads.values() if not p.is_linked), None)
            if sinkpad is None:
                sinkpad = down.request_pad(PadDirection.SINK)
            srcpad.link(sinkpad)
        return self

    # -- messages ---------------------------------------------------------
    def post_message(self, kind: str, **data) -> None:
        if kind == "error":
            first = False
            with self._lock:
                if self._error is None:
                    self._error = data.get("error")
                    first = True
            self._eos_evt.set()  # unblock waiters
            if first:
                # black-box: any abort records the event and dumps the
                # last-N-seconds flight recording (rate-limited)
                from ..obs import events as _obs_events
                from ..obs.recorder import RECORDER
                _obs_events.emit("abort", source=self.name, level=10,
                                 error=repr(data.get("error")))
                RECORDER.dump_abort(f"{self.name}-abort")
        self.bus.post(Message(kind, data))

    def _sink_eos(self, sink: Element) -> None:
        with self._lock:
            self._sinks_eos.add(sink.name)
            sinks = [e for e in self.elements.values()
                     if isinstance(e, SinkElement)
                     and any(p.is_linked for p in e.sink_pads.values())]
            done = all(s.name in self._sinks_eos for s in sinks)
        if done:
            self.post_message("eos")
            self._eos_evt.set()

    # -- static analysis ---------------------------------------------------
    def validate(self):
        """Run pipelint (caps/shape inference + graph rules) over the
        unstarted graph; returns the :class:`analysis.Report`."""
        from ..analysis import analyze
        return analyze(self)

    # -- state ------------------------------------------------------------
    def start(self) -> "Pipeline":
        """READY->PLAYING: start non-sources first, then source threads.

        Validates the graph first (``validate_on_start``, default True):
        error findings raise :class:`PipelineValidationError` before any
        element starts; warnings are logged."""
        if self.validate_on_start:
            from ..analysis import PipelineValidationError
            report = self.validate()
            if report.errors:
                raise PipelineValidationError(report)
            for f in report.warnings:
                logger.warning("pipelint: %s", f)
        if self.fuse and self._fusion_plan is None:
            from ..fusion import fuse_pipeline
            try:
                self._fusion_plan = fuse_pipeline(self)
            except Exception:  # noqa: BLE001 -- never block launch on fusion
                logger.warning(
                    "fusion: planner failed; running unfused", exc_info=True)
        self._sinks_eos.clear()
        self._eos_evt.clear()
        self._error = None
        srcs = []
        for e in self.elements.values():
            if isinstance(e, SrcElement):
                srcs.append(e)
            else:
                e.start()
        for e in srcs:
            e.start()
        self.running = True
        from ..obs import metrics as _obs_metrics
        _obs_metrics.register_pipeline(self)
        return self

    def stop(self) -> "Pipeline":
        for e in self.elements.values():
            if isinstance(e, SrcElement):
                e.stop()
        for e in self.elements.values():
            if not isinstance(e, SrcElement):
                e.stop()
        self.running = False
        from ..obs import metrics as _obs_metrics
        _obs_metrics.unregister_pipeline(self)
        return self

    def drain(self, deadline: float = 10.0) -> bool:
        """Graceful teardown (vs ``stop()``'s hard cut): ask every
        element to stop admitting new work, flush everything already in
        flight through queues and the serve batcher behind the EOS
        barrier, settle pending client correlations, then stop. Returns
        True when EOS reached every sink inside ``deadline`` seconds —
        False means the flush timed out and stop() cut it short.

        Safe to call twice; a drain of a never-started pipeline just
        stops it."""
        t0 = time.monotonic()
        from ..obs import events as _obs_events
        _obs_events.emit("drain", source=self.name, level=20,
                         deadline_s=float(deadline))
        self.post_message("drain", deadline=deadline)
        for e in self.elements.values():
            try:
                e.drain()
            except Exception:  # noqa: BLE001 — drain is best-effort per element
                logger.warning("%s: drain hook failed", e.name,
                               exc_info=True)
        ok = False
        try:
            remaining = max(0.0, deadline - (time.monotonic() - t0))
            ok = bool(self._eos_evt.wait(remaining)) \
                and self._error is None
        finally:
            self.stop()
        return ok

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    def checkpointables(self) -> List[Element]:
        """Elements overriding :meth:`Element.snapshot_state` — the set
        Pipeline.snapshot collects from and Pipeline.restore feeds."""
        return [e for e in self.elements.values()
                if type(e).snapshot_state is not Element.snapshot_state]

    def snapshot(self, directory: str, retain: int = 3,
                 meta: Optional[Dict] = None) -> str:
        """Write one crash-consistent snapshot of every checkpointable
        element into the retain-N store at ``directory`` and return the
        published snapshot path. The pipeline must be quiesced (drained
        or preempted) first — element snapshot hooks read live state.

        Layout and integrity rules: checkpoint/store.py."""
        import os
        import pickle
        from ..checkpoint.store import SnapshotStore

        def writer(tmp: str) -> None:
            edir = os.path.join(tmp, "elements")
            os.makedirs(edir)
            for e in self.checkpointables():
                sdir = os.path.join(edir, f"{e.name}.d")
                os.makedirs(sdir)
                state = e.snapshot_state(sdir)
                if not os.listdir(sdir):
                    os.rmdir(sdir)
                if state is None:
                    continue
                with open(os.path.join(edir, f"{e.name}.blob"), "wb") as f:
                    f.write(pickle.dumps(state, protocol=4))

        full_meta = dict(meta or {})
        full_meta.setdefault("pipeline", self.name)
        full_meta.setdefault("elements", {
            e.name: type(e).__name__ for e in self.checkpointables()})
        return SnapshotStore(directory, retain=retain).save(
            writer, meta=full_meta)

    def restore(self, directory: str) -> Dict:
        """Rebuild element state from a snapshot BEFORE ``start()``.
        ``directory`` is either a store root (latest snapshot wins) or
        one ``snap-*`` directory. The snapshot is verified first — a
        truncated blob or tampered manifest raises
        :class:`~nnstreamer_tpu.checkpoint.store.SnapshotError` naming
        the bad blob, and NO element state is touched (never a silent
        partial restore). Returns the snapshot's meta dict."""
        import os
        import pickle
        from ..checkpoint.store import (MANIFEST, SnapshotError,
                                        SnapshotStore)
        if self.running:
            raise RuntimeError(
                f"{self.name}: restore() must run before start()")
        snap = directory
        if not os.path.exists(os.path.join(snap, MANIFEST)):
            snap = SnapshotStore(directory).latest()
            if snap is None:
                raise SnapshotError(
                    f"no snapshot found under {directory!r}")
        manifest = SnapshotStore.verify(snap)
        edir = os.path.join(snap, "elements")
        for e in self.checkpointables():
            blob = os.path.join(edir, f"{e.name}.blob")
            if not os.path.exists(blob):
                continue  # element had no state at snapshot time
            with open(blob, "rb") as f:
                state = pickle.loads(f.read())
            e.restore_state(state, os.path.join(edir, f"{e.name}.d"))
        logger.info("%s: restored from %s (seq %s)", self.name, snap,
                    manifest.get("seq"))
        return manifest.get("meta", {})

    def preempt(self, grace_s: float, directory: str,
                retain: int = 3) -> Dict:
        """Preemption sequence: quiesce → bounded drain → snapshot →
        stop, all inside ``grace_s`` seconds.

        Every element's :meth:`~Element.preempt` hook runs first (cheap,
        non-blocking: stop admission, notify peers, pause the trainer).
        If the remaining grace — minus a reserve for writing the
        snapshot — allows, the pipeline waits for EOS to reach the sinks
        (a full drain). Otherwise it degrades: the snapshot is taken
        WITHOUT drain and every element's :meth:`~Element.preempt_inflight`
        count is recorded as explicitly abandoned — declared in the
        report, the snapshot meta, and each element's
        ``preempt_abandoned`` counter, never silent (the PR 7 accounting
        identity extends across process death).

        Returns ``{"snapshot", "drained", "abandoned", "grace_s",
        "used_s"}``."""
        t0 = time.monotonic()
        from ..obs import events as _obs_events
        from ..obs.recorder import RECORDER
        _obs_events.emit("preempt", source=self.name,
                         grace_s=float(grace_s))
        # the black-box dump is deliberate here (force past the abort
        # rate limit): a preemption is the canonical "what was the
        # fleet doing in its last seconds" question
        RECORDER.dump_abort(f"{self.name}-preempt", force=True)
        self.post_message("preempt", grace_s=grace_s)
        for e in self.elements.values():
            try:
                e.preempt()
            except Exception:  # noqa: BLE001 — quiesce is best-effort per element
                logger.warning("%s: preempt hook failed", e.name,
                               exc_info=True)
        # reserve a slice of the grace budget for the snapshot itself;
        # a short grace (< ~1s) degrades straight to snapshot-no-drain
        reserve = min(1.0, grace_s * 0.5)
        budget = grace_s - reserve - (time.monotonic() - t0)
        drained = budget > 0 and bool(self._eos_evt.wait(budget)) \
            and self._error is None
        abandoned: Dict[str, int] = {}
        if not drained:
            for e in self.elements.values():
                try:
                    n = int(e.preempt_inflight())
                except Exception:  # noqa: BLE001
                    n = 0
                if n > 0:
                    abandoned[e.name] = n
                    e.stats.inc("preempt_abandoned", n)
        snap = None
        try:
            snap = self.snapshot(
                directory, retain=retain,
                meta={"preempt": {"grace_s": float(grace_s),
                                  "drained": drained,
                                  "abandoned": abandoned}})
        finally:
            self.stop()
        report = {"snapshot": snap, "drained": drained,
                  "abandoned": abandoned, "grace_s": float(grace_s),
                  "used_s": time.monotonic() - t0}
        self.post_message("preempted", **report)
        return report

    def wait_eos(self, timeout: Optional[float] = None) -> bool:
        """Block until all sinks saw EOS or an error was posted.
        Returns True on clean EOS; raises on pipeline error."""
        ok = self._eos_evt.wait(timeout)
        if self._error is not None:
            raise self._error
        return ok

    def run(self, timeout: Optional[float] = None) -> "Pipeline":
        """start + wait_eos + stop (the gst-launch usage pattern)."""
        self.start()
        try:
            self.wait_eos(timeout)
        finally:
            self.stop()
        return self

    # -- introspection ----------------------------------------------------
    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-element counter snapshots, each internally consistent
        (taken under the element's Counters lock)."""
        return {name: e.stats.snapshot()
                for name, e in self.elements.items()}

    def __repr__(self) -> str:
        return f"<Pipeline {self.name!r} elements={list(self.elements)}>"
