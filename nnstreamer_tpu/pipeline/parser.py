"""gst-launch-style textual pipeline parser.

Builds a Pipeline from the same description syntax the reference's users
write (ref: pipelines are constructed with gst_parse_launch throughout the
reference's tests and docs, e.g. tests/nnstreamer_filter_tensorflow2_lite/
runTest.sh). Supported grammar:

    chain    := node (" ! " node)*
    node     := KIND prop*            create element
              | NAME "." [PAD]        reference a named element('s pad)
              | CAPS                  inline caps -> capsfilter
    prop     := KEY "=" VALUE         (VALUE may be quoted)

Branching works like gst-launch: ``tee name=t ! q1 ... t. ! q2 ...`` and
``src ! m.sink_1`` to target a named pad of a mux.

Every parse error reports the token index and the offending token, so a
long description can be debugged without counting whitespace by hand.
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple

from .element import Element
from .pad import PadDirection
from .pipeline import Pipeline
from .registry import make_element

_PROP_RE = re.compile(r"^([A-Za-z][\w-]*)=(.*)$", re.S)
_REF_RE = re.compile(r"^([A-Za-z][\w-]*)\.([\w%-]*)$")


def _tokenize(desc: str) -> List[str]:
    toks, cur, quote, qpos = [], [], None, -1
    for pos, ch in enumerate(desc):
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote, qpos = ch, pos
            cur.append(ch)
        elif ch.isspace():
            if cur:
                toks.append("".join(cur))
                cur = []
        else:
            cur.append(ch)
    if quote:
        raise ValueError(
            f"unterminated {quote} quote starting at character {qpos} "
            f"near {desc[max(0, qpos - 15):qpos + 15]!r}")
    if cur:
        toks.append("".join(cur))
    return toks


def _unquote(v: str) -> str:
    if len(v) >= 2 and v[0] == v[-1] and v[0] in "\"'":
        return v[1:-1]
    return v


def _is_caps_token(tok: str) -> bool:
    head = tok.split(",", 1)[0]
    return "/" in head and "=" not in head


def _free_src_pad(elem: Element):
    for p in elem.src_pads.values():
        if not p.is_linked:
            return p
    return elem.request_pad(PadDirection.SRC)


def _free_sink_pad(elem: Element, padname: Optional[str] = None):
    if padname:
        return elem.get_static_or_request_pad(padname, PadDirection.SINK)
    for p in elem.sink_pads.values():
        if not p.is_linked:
            return p
    return elem.request_pad(PadDirection.SINK)


def parse_launch(desc: str, pipeline: Optional[Pipeline] = None) -> Pipeline:
    pipe = pipeline if pipeline is not None else Pipeline()
    tokens = _tokenize(desc)
    current: Optional[Element] = None
    pending_link = False

    def _err(i: int, message: str) -> ValueError:
        return ValueError(f"token {i} ({tokens[i]!r}): {message}")

    def _rename(i: int, elem: Element, new: str) -> None:
        if new in pipe.elements:
            raise _err(i, f"duplicate element name {new!r}")
        del pipe.elements[elem.name]
        elem.name = new
        pipe.elements[new] = elem

    for i, tok in enumerate(tokens):
        if tok == "!":
            if current is None:
                raise _err(i, "'!' with no upstream element")
            pending_link = True
            continue

        ref = _REF_RE.match(tok)
        if ref and not _is_caps_token(tok):
            name, padname = ref.group(1), ref.group(2) or None
            if name not in pipe.elements:
                raise _err(i, f"reference to unknown element {name!r}")
            target = pipe.elements[name]
            if pending_link:
                _free_src_pad(current).link(_free_sink_pad(target, padname))
                pending_link = False
                current = target
            else:
                current = target  # start a new chain from this element
            continue

        m = _PROP_RE.match(tok)
        if m and not _is_caps_token(tok) and not pending_link and current is not None:
            key, val = m.group(1), _unquote(m.group(2))
            if key == "name":
                _rename(i, current, val)
            else:
                try:
                    current.set_property(key, val)
                except ValueError as exc:
                    raise _err(i, str(exc)) from None
            continue

        # element creation (kind or inline caps)
        if _is_caps_token(tok):
            elem = make_element("capsfilter", caps=_unquote(tok))
        else:
            if m:
                # pipeline-level props: a leading KEY=VALUE before any
                # element configures the Pipeline itself (gst-launch has
                # no analog; we use it for the fusion opt-out:
                # ``fuse=false src ! ...``).
                if current is None and m.group(1) == "fuse":
                    pipe.fuse = _unquote(m.group(2)).lower() not in (
                        "false", "0", "no", "off")
                    continue
                raise _err(i, f"property {tok!r} with no element to "
                              f"apply to")
            try:
                elem = make_element(tok)
            except ValueError as exc:
                raise _err(i, str(exc)) from None
        pipe.add(elem)
        if pending_link:
            _free_src_pad(current).link(_free_sink_pad(elem))
            pending_link = False
        current = elem

    if pending_link:
        raise ValueError(
            f"dangling '!' at end of description (token {len(tokens) - 1})")
    return pipe
