"""Element factory registry (≙ the GST_PLUGIN_DEFINE element registerer,
ref: gst/nnstreamer/registerer/nnstreamer.c:91-121).

Elements register by name with the ``@register_element`` decorator; the
launch-string parser instantiates through :func:`make_element`.
"""
from __future__ import annotations

from typing import Dict, Type

_ELEMENTS: Dict[str, type] = {}


def register_element(name: str):
    def deco(cls: type) -> type:
        if name in _ELEMENTS and _ELEMENTS[name] is not cls:
            raise ValueError(f"element name {name!r} already registered")
        _ELEMENTS[name] = cls
        cls.ELEMENT_NAME = name
        return cls
    return deco


# core plumbing elements exempt from the allowlist: the reference's
# element restriction (enable_element_restriction) governs nnstreamer
# elements only — gst core elements (queue, tee, appsrc, ...) are never
# restricted there, so a tensor_*-only allowlist must not break plumbing
_IMPLICIT = frozenset({
    "capsfilter", "queue", "tee", "identity", "appsrc", "appsink",
    "fakesink", "tensortestsrc", "videotestsrc", "audiotestsrc",
    "filesrc", "filesink", "multifilesrc", "multifilesink",
    "videoconvert", "videoscale", "pngdec",
})


def make_element(kind: str, name=None, **props):
    from ..utils.conf import conf
    if kind not in _IMPLICIT and not conf.element_allowed(kind):
        # product element allowlisting (≙ enable_element_restriction,
        # meson_options.txt:52-53)
        raise ValueError(f"element {kind!r} is restricted by configuration")
    try:
        cls = _ELEMENTS[kind]
    except KeyError:
        import difflib
        close = difflib.get_close_matches(kind, _ELEMENTS, n=3, cutoff=0.6)
        hint = (f"did you mean {', '.join(repr(c) for c in close)}?"
                if close else f"known: {sorted(_ELEMENTS)}")
        raise ValueError(f"no such element {kind!r}; {hint}") from None
    return cls(name=name, **props)


def element_names():
    return sorted(_ELEMENTS)


def get_element_class(kind: str) -> type:
    return _ELEMENTS[kind]
