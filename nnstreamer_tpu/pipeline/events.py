"""In-band pipeline events.

Downstream-serialized events modeled on GStreamer's: STREAM_START, CAPS,
SEGMENT, EOS, plus custom events (ref: GStreamer event model; the reference
relies on gst events for caps negotiation and EOS propagation, e.g.
gsttensor_trainer.c EOS handling).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..tensors.caps import Caps


class Event:
    """Base class for in-band events (flow downstream with buffers)."""

    __slots__ = ()


@dataclass
class StreamStart(Event):
    stream_id: str = "stream0"


@dataclass
class CapsEvent(Event):
    caps: Caps


@dataclass
class SegmentEvent(Event):
    """New segment: base running time in ns."""

    base_time: int = 0
    rate: float = 1.0


@dataclass
class EosEvent(Event):
    pass


@dataclass
class FlushEvent(Event):
    pass


@dataclass
class CustomEvent(Event):
    name: str
    data: Dict[str, Any] = field(default_factory=dict)


@dataclass
class QosEvent(Event):
    """Upstream QoS feedback (≙ GST_EVENT_QOS as consumed by the
    reference's tensor_filter throttling, tensor_filter.c:532-584).

    ``proportion`` > 1 means downstream is falling behind (it received
    frames faster than it can emit them); ``period_ns`` is the minimum
    inter-frame spacing downstream can sustain (the throttling delay).
    Travels upstream, out-of-band (not through queues).
    """

    proportion: float = 1.0
    period_ns: int = 0
    timestamp: Optional[int] = None


EOS = EosEvent
